"""The TPU batch ed25519 verification kernel — the framework's flagship op.

Device side of the reference's ``PubKeyUtils::verifySig``
(``src/crypto/SecretKey.cpp:435-468``): given a batch of (pubkey, R, s, h)
— with ``h = SHA512(R||A||M) mod L`` computed host-side (hashing is cheap
and sequential; see ``stellar_tpu/crypto/batch_verifier.py``) — checks the
cofactorless group equation ``encode(s*B - h*A) == R`` for every element in
parallel. Policy checks that are pure byte predicates (canonical s < L,
canonical A, small-order blocklist) are done host-side, exactly mirroring
libsodium's decomposition; the final verdict is the AND of both halves.

Shapes: batch rides the trailing axis of every limb array so it maps to the
128-wide TPU vector lanes; the kernel is shape-polymorphic in batch and is
jit-cached per padded bucket size. Multi-chip: the batch axis is sharded
with ``shard_map`` over a 1-D device mesh (pure data parallelism — no
collectives needed, verification is embarrassingly parallel; see
``stellar_tpu.parallel.mesh``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from stellar_tpu.ops import edwards as ed

__all__ = ["verify_kernel", "verify_kernel_sharded", "signed_digits16_dev"]


def signed_digits16_dev(b):
    """(batch, 32) uint8 little-endian scalars -> (64, batch) int32 SIGNED
    radix-16 digits, most significant first: the ref10 signed-window
    recode (libsodium ge25519_scalarmult's slide), vectorized. Runs on
    device so the host ships raw 32-byte scalars (4x less relay/PCIe
    traffic than int32 digit arrays).

    Digits d_i satisfy sum(d_i * 16^i) == s exactly for EVERY 256-bit s,
    with d_i in [-8, 8) for i < 63; the top digit absorbs the final carry
    unsigned, so it stays in [0, 2] for canonical scalars (s < L < 2^253)
    and in [0, 8] for any s < 2^255 — within the 8-entry table range of
    :func:`stellar_tpu.ops.edwards.table_select`. (Scalars >= 9 * 2^252
    overflow the top window; the host canonical-s gate rejects them before
    the verdict, see double_scalarmult's contract.)

    The nibble carry chain (c_{i+1} = 1 iff e_i + c_i >= 8) is a classic
    generate/propagate recurrence — generate at e_i >= 8, propagate at
    e_i == 7 — computed in log2(64) = 6 parallel steps with
    ``lax.associative_scan`` instead of a 64-long sequential chain.
    """
    x = b.astype(jnp.int32)
    lo = x & 15
    hi = x >> 4
    # (64, batch) unsigned nibbles, LEAST significant first
    e = jnp.stack([lo, hi], axis=2).reshape(b.shape[0], 64).T
    gen = e >= 8
    prop = e == 7

    def comb(lo_pair, hi_pair):
        g1, p1 = lo_pair
        g2, p2 = hi_pair
        return g2 | (p2 & g1), p2 & p1

    g_pre, _ = lax.associative_scan(comb, (gen, prop), axis=0)
    carry_out = g_pre.astype(jnp.int32)                # c_{i+1}, i = 0..63
    carry_in = jnp.concatenate(                        # c_i
        [jnp.zeros_like(carry_out[:1]), carry_out[:-1]], axis=0)
    # d_i = e_i + c_i - 16*c_{i+1}, except the top digit keeps its carry
    # (unsigned residue) so the recode reconstructs every 256-bit value.
    not_top = (jnp.arange(64, dtype=jnp.int32) < 63).astype(jnp.int32)
    d = e + carry_in - 16 * carry_out * not_top[:, None]
    return d[::-1]


def dsm_stage(s_bytes, h_bytes, a_neg):
    """Signed-window recode + double-scalarmult: the traceable 'dsm' stage
    of the kernel (tools/kernel_cost.py accounts cost per stage; the
    limb layout, window scheme, and MAC ledger live in
    docs/kernel_design.md)."""
    return ed.double_scalarmult(
        signed_digits16_dev(s_bytes), signed_digits16_dev(h_bytes), a_neg)


def verify_kernel(a_bytes, r_bytes, s_bytes, h_bytes):
    """Batched group-equation check.

    Args:
      a_bytes: (batch, 32) uint8 — public key encodings.
      r_bytes: (batch, 32) uint8 — signature R halves.
      s_bytes: (batch, 32) uint8 — signature scalars s (little-endian).
      h_bytes: (batch, 32) uint8 — h = SHA512(R||A||M) mod L (little-endian).

    Returns:
      (batch,) bool — True where decompression succeeded and
      encode(s*B + h*(-A)) == R bytewise. The scalar mult runs signed
      radix-16 windows (8-entry tables + conditional negate): exact for
      every s < 2^255, and the composed verifier decision stays
      bit-identical to libsodium because s >= L never reaches a verdict
      (host canonical-s gate).
    """
    ok, a = ed.decompress(a_bytes)
    rprime = dsm_stage(s_bytes, h_bytes, ed.negate(a))
    return ok & ed.compress_equals(rprime, r_bytes)


def verify_kernel_sharded(mesh, axis_name="batch"):
    """Wrap the kernel in shard_map over a 1-D mesh: batch split across
    devices, no cross-device communication (each chip verifies its shard).
    Returns a jitted callable with the same signature as verify_kernel;
    batch must be divisible by mesh size.

    Note: ``BatchVerifier`` no longer dispatches through this wrapper —
    it splits buckets into per-device sub-chunks of the plain kernel so
    failures are attributable to ONE chip (the fault-domain boundary,
    ``docs/robustness.md``). This stays as the single-call collective
    layout for harnesses (``__graft_entry__.dryrun_multichip``) and
    mesh-layout experiments.
    """
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    sharded = shard_map(
        verify_kernel,
        mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name, None),
                  P(axis_name, None), P(axis_name, None)),
        out_specs=P(axis_name),
        check_rep=False,
    )
    return jax.jit(sharded)
