"""Batched twisted-Edwards (ed25519) group operations in JAX for TPU.

Points are extended homogeneous coordinates ``(X, Y, Z, T)`` with
``x = X/Z, y = Y/Z, x*y = T/Z``; each coordinate is a GF(2^255-19) limb
array of shape ``(20, *batch)`` (see :mod:`stellar_tpu.ops.field25519`).
All formulas are the *complete* RFC 8032 / "hwcd" unified formulas (valid
for every pair of curve points, including identity and equal inputs), so
there is no data-dependent control flow anywhere — everything maps to
straight-line VPU code under ``jit``.

This is the group layer under the batch signature verifier
(:mod:`stellar_tpu.ops.verify`), the TPU-native replacement for the
reference's libsodium ge25519 layer (reference: the verify path behind
``PubKeyUtils::verifySig``, ``src/crypto/SecretKey.cpp:435-468``).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from stellar_tpu.ops import field25519 as fe
from stellar_tpu.crypto import ed25519_ref as ref

__all__ = [
    "identity", "point_add", "point_add_cached", "point_double",
    "to_cached", "decompress", "compress_equals",
    "negate", "select_point", "table_select", "table_select_affine",
    "base_table", "base_table_affine", "base_table_affine_wide",
    "build_point_table", "build_point_table_affine",
    "double_scalarmult_hot", "D_LIMBS",
    "D2_LIMBS", "SQRTM1_LIMBS", "unpack255",
]

# Window-method shape constants (read by tools/kernel_cost.py).
# Signed radix-16 (digits in [-8, 8)): each window select contracts over
# the 8 cached multiples 1..8 of the base; sign is a cheap cached-form
# negate and digit 0 a limb-0 identity fixup — HALF the one-hot MAC
# volume of the unsigned 16-entry scheme (see docs/kernel_design.md).
WINDOWS = 64       # radix-16 digits per 256-bit scalar
TABLE_ENTRIES = 8  # one-hot contraction entries per window select

# Signed radix-32 (PR 13, the landed default — see the radix-window
# sweep decision record in docs/kernel_design.md §3): 52 five-bit
# windows, 16-entry batched-AFFINE tables (Z normalized to exactly 1 by
# one Montgomery-batched inversion per table, fe.batch_inv), selected
# by a log-depth conditional-move tree (ref10 ge25519_select's masked
# cmov, not a one-hot contraction) — the multiply ledger carries zero
# select MACs and every A-window add takes the z2_is_one fast path.
WINDOWS32 = 52        # radix-32 digits per 256-bit scalar
TABLE_ENTRIES32 = 16  # cmov-tree entries per window select
AFFINE_COORDS = 3     # affine cached entry: (Y+X, Y-X, 2d*T); Z == 1

# Signed radix-256 (PR 16, the HOT-SIGNER loop — docs/kernel_design.md
# §5): 32 byte-aligned windows over 128-entry affine tables. The live
# radix-32 loop cannot afford windows this wide — a 128-entry per-batch
# table build would dwarf the doublings it saves — but a CACHED
# per-pubkey table amortizes its (host-side) build across the signer's
# lifetime, so the hot path pays only the loop: 248 doublings + 63 adds
# instead of 255 + 103 + the in-kernel table build. Tables are stored
# int16 (canonical 13-bit limbs fit with 3 bits to spare), halving the
# dispatch operand bytes; the cmov tree runs in int16 and the selected
# entry widens to int32 at the tree's root.
WINDOWS256 = 32        # radix-256 digits per 256-bit scalar
TABLE_ENTRIES256 = 128  # cmov-tree entries per hot window select

# Curve constants as canonical limb vectors (host numpy, broadcast at trace).
D_LIMBS = fe.from_int(ref.D)
D2_LIMBS = fe.from_int(2 * ref.D % ref.P)
SQRTM1_LIMBS = fe.from_int(ref.SQRT_M1)


def _const(limbs: np.ndarray, batch_shape):
    c = jnp.asarray(limbs).reshape((fe.NLIMBS,) + (1,) * len(batch_shape))
    return jnp.broadcast_to(c, (fe.NLIMBS,) + tuple(batch_shape))


def identity(batch_shape=()):
    z = fe.zeros(batch_shape)
    one = _const(fe.from_int(1), batch_shape)
    return (z, one, one, z)


def negate(p):
    x, y, z, t = p
    return (fe.neg(x), y, z, fe.neg(t))


def _mulstack(ls, rs):
    """N field multiplies fused into ONE stacked multiply over a
    (20, N, *batch) operand. The hot loop is bound by per-op overhead on
    small (20, batch) tensors, not FLOPs — dividing the op count by
    widening the batch axis is the single biggest lever on TPU."""
    o = fe.mul(jnp.stack(ls, axis=1), jnp.stack(rs, axis=1))
    return tuple(o[:, i] for i in range(len(ls)))


def _stack_points(ps):
    """Points (tuples of (20, *batch) coords) -> one point whose batch is
    (len(ps), *batch): same-shaped group ops fuse into one call."""
    return tuple(jnp.stack(cs, axis=1) for cs in zip(*ps))


def _unstack_points(p, n):
    return [tuple(c[:, i] for c in p) for i in range(n)]


def to_cached(p):
    """Extended point -> ref10 ``ge_cached`` form (Y+X, Y-X, Z, 2d*T):
    the representation table entries are stored in, making every
    window add exactly two fused multiplies."""
    x, y, z, t = p
    d2 = _const(D2_LIMBS, t.shape[1:])
    return (fe.add(y, x), fe.sub(y, x), z, fe.mul(t, d2))


def point_add_cached(p, q_cached, need_t=True, z2_is_one=False):
    """p (extended) + q (cached) — complete unified addition as two
    fused stacked multiplies (reference: libsodium ge25519_add).

    ``need_t=False`` returns a projective (X, Y, Z) triple, dropping the
    E*H lane of the output multiply — valid whenever the result only
    feeds doublings or encode (both ignore T).  ``z2_is_one`` drops the
    Z1*Z2 lane of the input multiply when q's Z is exactly 1 (the
    precomputed base table is stored affine). ``q_cached`` may be an
    AFFINE cached triple (Y+X, Y-X, 2d*T) — Z == 1 is implied, so the
    triple always takes the fast path (batched-affine A-tables,
    :func:`build_point_table_affine`)."""
    x1, y1, z1, t1 = p[0], p[1], p[2], p[3]
    if len(q_cached) == AFFINE_COORDS:
        ypx2, ymx2, t2d2 = q_cached
        z2, z2_is_one = None, True
    else:
        ypx2, ymx2, z2, t2d2 = q_cached
    if z2_is_one:
        a, b, c = _mulstack((fe.sub(y1, x1), fe.add(y1, x1), t1),
                            (ymx2, ypx2, t2d2))
        dd = z1
    else:
        a, b, c, dd = _mulstack((fe.sub(y1, x1), fe.add(y1, x1), t1, z1),
                                (ymx2, ypx2, t2d2, z2))
    dd = fe.add(dd, dd)
    e = fe.sub(b, a)
    f = fe.sub(dd, c)
    g = fe.add(dd, c)
    h = fe.add(b, a)
    if need_t:
        return _mulstack((e, g, f, e), (f, h, g, h))
    return _mulstack((e, g, f), (f, h, g))


def point_add(p, q):
    """Complete unified addition of two extended points."""
    return point_add_cached(p, to_cached(q))


def point_double(p, need_t=True):
    """Dedicated doubling; one fused squaring + one fused multiply.

    Accepts an extended (X, Y, Z, T) or projective (X, Y, Z) point — T is
    never read.  ``need_t=False`` drops the E*H output lane and returns a
    projective triple: in a doubling chain only the LAST double before a
    cached add needs T, so chained doubles run 3-wide, not 4-wide."""
    x1, y1, z1 = p[0], p[1], p[2]
    s = fe.sqr(jnp.stack([x1, y1, z1, fe.add(x1, y1)], axis=1))
    a, b, zz, xysq = s[:, 0], s[:, 1], s[:, 2], s[:, 3]
    c = fe.add(zz, zz)
    h = fe.add(a, b)
    e = fe.sub(h, xysq)
    g = fe.sub(a, b)
    f = fe.add(c, g)
    if need_t:
        return _mulstack((e, g, f, e), (f, h, g, h))
    return _mulstack((e, g, f), (f, h, g))


def select_point(cond, p, q):
    """Per-batch-element point select: cond (batch,) -> p where true."""
    return tuple(fe.select(cond, a, b) for a, b in zip(p, q))


def unpack255(b):
    """(batch, 32) uint8 little-endian -> ((20, batch) limbs of low 255
    bits, (batch,) int32 top bit). Limbs are strict 13-bit digits."""
    nbatch = b.shape[0]
    bits = ((b[:, :, None].astype(jnp.int32)
             >> jnp.arange(8, dtype=jnp.int32)) & 1)
    bits = bits.reshape(nbatch, 256)
    sign = bits[:, 255]
    bits = bits * (jnp.arange(256) != 255).astype(jnp.int32)
    bits = jnp.pad(bits, ((0, 0), (0, 260 - 256)))
    weights = (1 << jnp.arange(fe.BITS, dtype=jnp.int32))
    limbs = (bits.reshape(nbatch, fe.NLIMBS, fe.BITS) * weights).sum(-1)
    return limbs.T, sign


def decompress(a_bytes):
    """Batched ge25519_frombytes: (batch, 32) uint8 -> (ok, point).

    Mirrors libsodium's frombytes math (y taken mod p implicitly; candidate
    square root via the (p-5)/8 exponent, corrected by sqrt(-1); "negative
    zero" x==0 with sign=1 rejected). Canonicity/small-order policy checks
    live host-side in :mod:`stellar_tpu.crypto.batch_verifier`, matching the
    split in the reference (`crypto/SecretKey.cpp:435-468`).
    """
    y, sign = unpack255(a_bytes)
    batch = y.shape[1:]
    one = _const(fe.from_int(1), batch)
    y2 = fe.sqr(y)
    u = fe.sub(y2, one)
    v = fe.add(fe.mul(y2, _const(D_LIMBS, batch)), one)
    v3 = fe.mul(fe.sqr(v), v)
    v7 = fe.mul(fe.sqr(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow22523(fe.mul(u, v7)))
    vxx = fe.canon(fe.mul(fe.sqr(x), v))
    u_c = fe.canon(u)
    negu_c = fe.canon(fe.neg(u))
    ok_direct = (vxx == u_c).all(axis=0)
    ok_twist = (vxx == negu_c).all(axis=0)
    x = fe.select(ok_twist, fe.mul(x, _const(SQRTM1_LIMBS, batch)), x)
    ok = ok_direct | ok_twist
    x_c = fe.canon(x)
    x_zero = (x_c == 0).all(axis=0)
    ok = ok & ~(x_zero & (sign == 1))
    flip = (x_c[0] & 1) != sign
    x = fe.select(flip, fe.neg(x), x)
    t = fe.mul(x, y)
    return ok, (x, y, _const(fe.from_int(1), batch), t)


def compress_equals(p, r_bytes):
    """encode(p) == r_bytes, batched, without materializing bytes.

    The encoding of p is always canonical, and ``unpack255`` yields the
    exact digits of r's 255-bit integer, so canonical-limb equality plus
    sign-bit equality is exactly libsodium's bytewise crypto_verify_32.
    Accepts an extended (X, Y, Z, T) or projective (X, Y, Z) point — the
    double-scalarmult loop returns projective, T never being read here.
    """
    x, y, z = p[0], p[1], p[2]
    zinv = fe.inv(z)
    xa = fe.canon(fe.mul(x, zinv))
    ya = fe.canon(fe.mul(y, zinv))
    ry, rsign = unpack255(r_bytes)
    return ((ya == ry).all(axis=0)) & ((xa[0] & 1) == rsign)


def table_select(table, digit):
    """table (8, 4, 20, *batch) cached multiples 1*P..8*P; digit (*batch,)
    int32 SIGNED window digit in [-8, 8) -> cached point |digit|*P
    conditionally negated.

    One-hot multiply-accumulate over the 8 positive multiples — branchless,
    constant-shape, VPU-friendly (a gather would lower to a serial
    dynamic-slice loop on TPU) — at HALF the MAC volume of the unsigned
    16-entry contraction. Digit 0 matches no entry and leaves zeros; the
    cached identity (1, 1, 1, 0) is patched in with three limb-0 adds.
    Negative digits cost one cached-form negate: swap Y+X <-> Y-X and
    negate 2dT (Z unchanged) — adds and selects, no extra multiplies.

    Batch-polymorphic: *batch may itself be stacked, e.g. (2, n) when the
    B- and A-table selects of the verify loop fuse into one contraction.
    """
    nb = digit.ndim
    mag = jnp.abs(digit)
    idx = jnp.arange(1, 9, dtype=jnp.int32).reshape((8,) + (1,) * nb)
    onehot = (idx == mag[None]).astype(jnp.int32)
    sel = (table * onehot[:, None, None]).sum(axis=0)  # (4, 20, *batch)
    ypx, ymx, z, t2d = sel[0], sel[1], sel[2], sel[3]
    is0 = (digit == 0).astype(jnp.int32)
    ypx = ypx.at[0].add(is0)
    ymx = ymx.at[0].add(is0)
    z = z.at[0].add(is0)
    neg = digit < 0
    return (fe.select(neg, ymx, ypx), fe.select(neg, ypx, ymx), z,
            fe.select(neg, fe.neg(t2d), t2d))


def _host_affine_cached_row(v: int) -> tuple:
    """v*B normalized to affine and packed as canonical cached limbs
    (y+x, y-x, 2d*x*y) — the ONE place the host-side cached-form
    convention lives; both base-table layouts derive from it."""
    pt = ref.point_mul(v, ref.BASE)
    zinv = ref._inv(pt[2])
    x = pt[0] * zinv % ref.P
    y = pt[1] * zinv % ref.P
    return (fe.from_int((y + x) % ref.P),
            fe.from_int((y - x) % ref.P),
            fe.from_int(2 * ref.D * x * y % ref.P))


def _base_multiples() -> np.ndarray:
    """Host-precomputed v*B for v in 1..8 in CACHED form (y+x, y-x, 1,
    2d*x*y) canonical limbs, shape (8, 4, 20) int32. Z is exactly 1, so
    base-table adds may use the ``z2_is_one`` fast path."""
    out = np.zeros((TABLE_ENTRIES, 4, fe.NLIMBS), dtype=np.int32)
    for v in range(1, TABLE_ENTRIES + 1):
        ypx, ymx, t2d = _host_affine_cached_row(v)
        out[v - 1, 0] = ypx
        out[v - 1, 1] = ymx
        out[v - 1, 2] = fe.from_int(1)
        out[v - 1, 3] = t2d
    return out


_BASE_TABLE = _base_multiples()


def base_table(batch_shape):
    """(8, 4, 20, *batch) broadcast constant cached table of v*B, v=1..8."""
    t = jnp.asarray(_BASE_TABLE).reshape(
        (TABLE_ENTRIES, 4, fe.NLIMBS) + (1,) * len(batch_shape))
    return jnp.broadcast_to(
        t, (TABLE_ENTRIES, 4, fe.NLIMBS) + tuple(batch_shape))


def build_point_table(p):
    """Per-batch cached table v*p for v in 1..8 -> (8, 4, 20, *batch).

    Seven group ops instead of the old fourteen sequential adds, scheduled
    so same-shaped ops fuse (ref10 ge25519_scalarmult's precompute DAG):

        2 = dbl(1); 4 = dbl(2); {3, 5} = {2, 4} + 1 (one stacked add);
        {6, 8} = dbl({3, 4}) (one stacked double); 7 = 6 + 1

    — five fused kernel calls, dependency depth 5 instead of 14, and one
    stacked ``to_cached`` over all 8 entries instead of 8 separate ones.
    """
    c1 = to_cached(p)
    p2 = point_double(p)
    p4 = point_double(p2)
    p3, p5 = _unstack_points(
        point_add_cached(_stack_points([p2, p4]), _stack_points([c1, c1])),
        2)
    p6, p8 = _unstack_points(point_double(_stack_points([p3, p4])), 2)
    p7 = point_add_cached(p6, c1)
    cached = to_cached(_stack_points([p, p2, p3, p4, p5, p6, p7, p8]))
    # (4, 20, 8, *batch) -> (8, 4, 20, *batch)
    return jnp.moveaxis(jnp.stack(cached), 2, 0)


def _extended_multiples(p, entries=16):
    """Per-batch extended points [1*p .. entries*p] via the even/odd
    ladder: each round doubles every v with 2v missing (one stacked
    double) and adds p to every even with v+1 missing (one stacked
    cached add) — 2v = dbl(v), 2v+1 = 2v + 1. Doubles run 4-wide only
    when some odd successor will read the T lane; adds always drop it
    (the affine normalization recomputes T from the inverted Z).

    ``entries`` and the round schedule (``have``) are compile-time
    Python values — the hotpath lint's taint model needs the schedule
    separated from the traced point dict to see that."""
    c1 = to_cached(p)
    pts = {1: p}
    have = {1}
    while len(have) < entries:
        dbl_src = [v for v in sorted(have)
                   if 2 * v <= entries and 2 * v not in have]
        if dbl_src:
            need_t = any(2 * v + 1 <= entries for v in dbl_src)
            doubled = _unstack_points(point_double(
                _stack_points([pts[v] for v in dbl_src]),
                need_t=need_t), len(dbl_src))
            for i in range(len(dbl_src)):
                pts[2 * dbl_src[i]] = doubled[i]
            have.update(2 * v for v in dbl_src)
        add_src = [v for v in sorted(have) if v % 2 == 0
                   and v + 1 <= entries and v + 1 not in have]
        if add_src:
            summed = _unstack_points(point_add_cached(
                _stack_points([pts[v] for v in add_src]),
                _stack_points([c1] * len(add_src)), need_t=False),
                len(add_src))
            for i in range(len(add_src)):
                pts[add_src[i] + 1] = summed[i]
            have.update(v + 1 for v in add_src)
    return [pts[v] for v in range(1, entries + 1)]


def build_point_table_affine(p, entries=TABLE_ENTRIES32):
    """Per-batch AFFINE cached table v*p, v in 1..entries ->
    (entries, 3, 20, *batch) with coords (Y+X, Y-X, 2d*T) and Z == 1
    exactly: the ladder's projective Z column is normalized away by ONE
    Montgomery-batched inversion (:func:`fe.batch_inv` — prefix
    products over the entry axis stacked on the fused-multiply axis,
    one true inversion for the whole call, back-substitution), so every
    window add against this table takes the ``z2_is_one`` fast path
    that previously only the precomputed base table enjoyed."""
    pts = _extended_multiples(p, entries)
    xs = jnp.stack([q[0] for q in pts], axis=1)   # (20, E, *batch)
    ys = jnp.stack([q[1] for q in pts], axis=1)
    zs = jnp.stack([q[2] for q in pts], axis=1)
    zinv = fe.batch_inv(zs)
    # affine ypx/ymx plus T = X*Y/Z^2 * Z = (X/Z)*(Y/Z): with u = X*zi,
    # v = Y*zi, t = u*v needs a second pass — cheaper as ONE 3-wide
    # stacked multiply by zinv of (X+Y, Y-X, T') where T' is the
    # ladder's projective T... T was dropped (need_t=False) for odd and
    # terminal entries, so recompute t = (X*zi)*(Y*zi) instead: one
    # 2-wide multiply, one 1-wide, one d2 scale.
    uv = fe.mul(jnp.stack([xs, ys], axis=1),
                jnp.stack([zinv, zinv], axis=1))  # (20, 2, E, *batch)
    u, v = uv[:, 0], uv[:, 1]
    t2d = fe.mul(fe.mul(u, v), _const(D2_LIMBS, u.shape[1:]))
    cached = jnp.stack([fe.add(v, u), fe.sub(v, u), t2d])  # (3,20,E,..)
    return jnp.moveaxis(cached, 2, 0)  # (E, 3, 20, *batch)


def _affine_multiples_host(entries=16) -> np.ndarray:
    """Host-precomputed v*B, v in 1..entries, affine cached (Y+X, Y-X,
    2d*X*Y) canonical limbs, shape (entries, 3, 20) int32 (``entries``
    is a host-side Python int — module-level precompute only). Rows
    come from the same :func:`_host_affine_cached_row` as the radix-16
    base table, so the two layouts can never desynchronize."""
    out = np.zeros((entries, AFFINE_COORDS, fe.NLIMBS), dtype=np.int32)
    for v in range(1, entries + 1):
        out[v - 1] = np.stack(_host_affine_cached_row(v))
    return out


_BASE_TABLE32 = _affine_multiples_host(TABLE_ENTRIES32)


def base_table_affine(batch_shape):
    """(16, 3, 20, *batch) broadcast constant affine cached table of
    v*B, v = 1..16 (the radix-32 loop's B-table)."""
    t = jnp.asarray(_BASE_TABLE32).reshape(
        (TABLE_ENTRIES32, AFFINE_COORDS, fe.NLIMBS)
        + (1,) * len(batch_shape))
    return jnp.broadcast_to(
        t, (TABLE_ENTRIES32, AFFINE_COORDS, fe.NLIMBS)
        + tuple(batch_shape))


# The hot-signer loop's B-table: v*B for v = 1..128, affine cached,
# int16 (canonical limbs are 13-bit). Built with the SAME host rows as
# every other precomputed table (ref.affine_table_rows — an incremental
# chain + one batched inversion, so the 128-entry build costs
# milliseconds at import, not 128 full scalar-mults).
_BASE_TABLE256 = np.array(
    [[fe.from_int(c) for c in row]
     for row in ref.affine_table_rows(ref.BASE, TABLE_ENTRIES256)],
    dtype=np.int16)


def base_table_affine_wide(batch_shape):
    """(128, 3, 20, *batch) broadcast constant affine cached table of
    v*B, v = 1..128, int16 (the hot-signer radix-256 loop's B-table —
    same rows a cached signer table carries for -A)."""
    t = jnp.asarray(_BASE_TABLE256).reshape(
        (TABLE_ENTRIES256, AFFINE_COORDS, fe.NLIMBS)
        + (1,) * len(batch_shape))
    return jnp.broadcast_to(
        t, (TABLE_ENTRIES256, AFFINE_COORDS, fe.NLIMBS)
        + tuple(batch_shape))


def table_select_affine(table, digit):
    """table (entries, 3, 20, *batch) affine cached multiples
    1*P..entries*P; digit (*batch,) int32 SIGNED window digit with
    |digit| <= entries -> affine cached triple |digit|*P conditionally
    negated. ``entries`` must be a power of two — 16 for the radix-32
    loop, 128 (int16 storage) for the hot-signer radix-256 loop.

    A log-depth conditional-move tree over the 16 entries — ref10
    ge25519_select's masked cmov, vectorized: 4 levels of ``where`` on
    the magnitude's bits, each halving the entry axis. Branchless,
    constant-shape, VPU select/compare work with ZERO multiplies (the
    PR 1 one-hot contraction spent 82k MACs/verify here; the executed
    MAC ledger in docs/kernel_design.md §3 carries the select volume as
    logic elems instead). Digit 0 is patched to the affine cached
    identity (1, 1, 0) with one select; negative digits swap
    Y+X <-> Y-X and negate 2dT — adds and selects, no multiplies.

    Batch-polymorphic like :func:`table_select`: *batch may be stacked,
    e.g. (2, n) when the B- and A-table selects fuse."""
    nb = digit.ndim
    mag = jnp.abs(digit)
    # cmov tree on (mag - 1) clamped to [0, entries-1]; mag == 0 lands
    # on entry 1 and is overwritten by the identity patch below.
    m = jnp.maximum(mag - 1, 0)
    sel = table
    bit = table.shape[0]
    while bit > 1:
        bit //= 2
        top = (m >= bit)
        m = jnp.where(top, m - bit, m)
        sel = jnp.where(top[(None,) * (sel.ndim - nb)],
                        sel[bit:], sel[:bit])
    # int16 wide tables widen to the int32 compute dtype here (a no-op
    # for the int32 radix-32 table, so the cold jaxpr is unchanged)
    sel = sel[0].astype(jnp.int32)  # (3, 20, *batch)
    is0 = (digit == 0)
    ident = jnp.asarray(np.stack(
        [fe.from_int(1), fe.from_int(1), fe.from_int(0)])).reshape(
            (AFFINE_COORDS, fe.NLIMBS) + (1,) * nb)
    sel = jnp.where(is0[None, None], ident, sel)
    ypx, ymx, t2d = sel[0], sel[1], sel[2]
    neg = digit < 0
    return (fe.select(neg, ymx, ypx), fe.select(neg, ypx, ymx),
            fe.select(neg, fe.neg(t2d), t2d))


_HALF_LIMBS = fe.from_int((fe.P + 1) // 2)


def _extended_from_affine_cached(c):
    """Affine cached triple (Y+X, Y-X, 2d*T) -> extended (X, Y, 1, T):
    x = (ypx - ymx)/2, y = (ypx + ymx)/2, t = x*y. Seeds the radix-32
    loop's accumulator from the top window's B-entry without paying an
    identity + cached add (the identity triple (1, 1, 0) reconstructs
    to the identity point exactly)."""
    ypx, ymx, t2d = c
    batch = ypx.shape[1:]
    half = _const(_HALF_LIMBS, batch)
    xy = fe.mul(jnp.stack([fe.sub(ypx, ymx), fe.add(ypx, ymx)], axis=1),
                jnp.stack([half, half], axis=1))
    x, y = xy[:, 0], xy[:, 1]
    return (x, y, _const(fe.from_int(1), batch), fe.mul(x, y))


def double_scalarmult(s_digits, h_digits, a_neg):
    """R' = s*B + h*a_neg via Strauss-Shamir with SIGNED windows.

    The radix is inferred from the digit count: (52, batch) digits run
    the radix-32 batched-affine loop (:func:`_double_scalarmult32`, the
    landed default — see docs/kernel_design.md §3's sweep decision);
    (64, batch) digits run the PR 1 radix-16 loop
    (:func:`_double_scalarmult16`, kept traceable as the radix sweep's
    baseline arm and for the op-level differential suite). a_neg:
    extended point (the verifier passes -A). Returns a PROJECTIVE
    (X, Y, Z) triple — T is dropped lane-by-lane throughout because
    nothing downstream (doublings, encode) reads it.
    """
    if s_digits.shape[0] == WINDOWS32:
        return _double_scalarmult32(s_digits, h_digits, a_neg)
    return _double_scalarmult16(s_digits, h_digits, a_neg)


def _double_scalarmult16(s_digits, h_digits, a_neg):
    """Radix-16 Strauss-Shamir (PR 1): (64, batch) signed digits in
    [-8, 8), most significant first (the top digit may reach 8 for
    scalars < 2^255, and scalars >= 9 * 2^252 — always rejected by the
    host canonical-s gate — overflow the top window and yield a
    well-defined garbage result).

    252 shared doublings + 128 cached adds under one fori_loop. Per
    iteration: three 3-wide doubles, one 4-wide double, ONE fused
    8-entry one-hot contraction selecting both the B- and A-table
    windows (the pair rides a stacked batch axis), a z2=1 base add, and
    a full projective-table cached add.
    """
    batch = a_neg[0].shape[1:]
    tab_a = build_point_table(a_neg)
    tab_b = base_table(batch)
    tab = jnp.stack([tab_b, tab_a], axis=3)  # (8, 4, 20, 2, *batch)

    def body(j, acc):
        acc = point_double(acc, need_t=False)
        acc = point_double(acc, need_t=False)
        acc = point_double(acc, need_t=False)
        acc = point_double(acc)  # the adds below read T
        sd = lax.dynamic_index_in_dim(s_digits, j, 0, keepdims=False)
        hd = lax.dynamic_index_in_dim(h_digits, j, 0, keepdims=False)
        sel = table_select(tab, jnp.stack([sd, hd]))
        bsel = tuple(c[:, 0] for c in sel)
        asel = tuple(c[:, 1] for c in sel)
        acc = point_add_cached(acc, bsel, z2_is_one=True)
        return point_add_cached(acc, asel, need_t=False)

    return lax.fori_loop(0, 64, body, identity(batch)[:3])


def _double_scalarmult32(s_digits, h_digits, a_neg):
    """Radix-32 batched-affine Strauss-Shamir (PR 13, the hot loop):
    (52, batch) signed radix-32 digits in [-16, 16), most significant
    first (:func:`stellar_tpu.ops.verify.signed_digits32_dev`; the top
    digit absorbs the carry unsigned and stays <= 2 for EVERY 256-bit
    scalar, so — unlike the radix-16 arm — no scalar overflows its
    window).

    255 shared doublings + 103 cached adds, ALL of them fast-path:
    both tables are affine (the base table precomputed, the A-table
    normalized by one Montgomery-batched inversion per call in
    :func:`build_point_table_affine`), so every add runs 3-wide on the
    input multiply, and window selection is a multiply-free cmov tree
    (:func:`table_select_affine`). The top window skips its doublings
    entirely: the accumulator seeds from the selected B-entry
    reconstructed to extended form plus one A-add. Per loop iteration:
    four 3-wide doubles under an inner fori, one 4-wide double, one
    fused 16-entry cmov-tree select for the B+A pair, and two affine
    cached adds. Cost ledger: docs/kernel_design.md §3; enforced by
    tests/test_kernel_cost.py.
    """
    batch = a_neg[0].shape[1:]
    tab_a = build_point_table_affine(a_neg, TABLE_ENTRIES32)
    tab_b = base_table_affine(batch)
    tab = jnp.stack([tab_b, tab_a], axis=3)  # (16, 3, 20, 2, *batch)

    def select_pair(j):
        sd = lax.dynamic_index_in_dim(s_digits, j, 0, keepdims=False)
        hd = lax.dynamic_index_in_dim(h_digits, j, 0, keepdims=False)
        sel = table_select_affine(tab, jnp.stack([sd, hd]))
        return (tuple(c[:, 0] for c in sel),
                tuple(c[:, 1] for c in sel))

    # top window: no doublings on a fresh accumulator — seed it from
    # the B-entry directly and add the A-entry (T produced for the next
    # window's base add... which reads T off the in-loop 4-wide double,
    # so even this add can drop its T lane).
    bsel0, asel0 = select_pair(jnp.int32(0))
    acc = _extended_from_affine_cached(bsel0)
    acc = point_add_cached(acc, asel0, need_t=False)

    def body(j, acc):
        acc = lax.fori_loop(
            0, 4, lambda _, q: point_double(q, need_t=False), acc)
        acc = point_double(acc)  # the adds below read T
        bsel, asel = select_pair(j)
        acc = point_add_cached(acc, bsel)
        return point_add_cached(acc, asel, need_t=False)

    return lax.fori_loop(1, WINDOWS32, body, acc)


def double_scalarmult_hot(s_digits, h_digits, a_table):
    """R' = s*B + h*(-A) for HOT signers: radix-256 Strauss-Shamir over
    a device-RESIDENT 128-entry affine A-table (PR 16 — the per-pubkey
    table cache, :mod:`stellar_tpu.parallel.signer_tables`; layout and
    amortization math in docs/kernel_design.md §5).

    s_digits/h_digits: (32, batch) signed radix-256 digits, most
    significant first (:func:`stellar_tpu.ops.verify.signed_digits256_dev`;
    digits in [-128, 128) with the top digit unsigned — <= 32 for every
    gate-passed scalar < 2^253, so no canonical scalar overflows the
    128-entry tables; s >= L rows compute well-defined garbage that the
    host canonical-s gate has already vetoed). a_table: (128, 3, 20,
    *batch) int16 affine cached multiples 1..128 of -A, canonical limbs
    with Z == 1 exactly — built host-side ONCE per signer and replayed
    from the signer-table cache, so unlike the radix-32 loop no table
    build runs in-kernel at all.

    248 shared doublings + 63 cached adds, every add fast-path affine:
    per iteration seven 3-wide doubles under an inner fori, one 4-wide
    double, one fused 128-entry cmov-tree select (int16 until the
    tree's root) for the B+A pair, and two affine cached adds. The top
    window seeds the accumulator from its B-entry + one A-add, exactly
    like the radix-32 loop. Returns a PROJECTIVE (X, Y, Z) triple.
    Cost ledger: ``dsm.hot`` rows in tools/kernel_cost.py."""
    batch = s_digits.shape[1:]
    tab_b = base_table_affine_wide(batch)
    tab = jnp.stack([tab_b, a_table], axis=3)  # (128, 3, 20, 2, *batch)

    def select_pair(j):
        sd = lax.dynamic_index_in_dim(s_digits, j, 0, keepdims=False)
        hd = lax.dynamic_index_in_dim(h_digits, j, 0, keepdims=False)
        sel = table_select_affine(tab, jnp.stack([sd, hd]))
        return (tuple(c[:, 0] for c in sel),
                tuple(c[:, 1] for c in sel))

    bsel0, asel0 = select_pair(jnp.int32(0))
    acc = _extended_from_affine_cached(bsel0)
    acc = point_add_cached(acc, asel0, need_t=False)

    def body(j, acc):
        acc = lax.fori_loop(
            0, 7, lambda _, q: point_double(q, need_t=False), acc)
        acc = point_double(acc)  # the adds below read T
        bsel, asel = select_pair(j)
        acc = point_add_cached(acc, bsel)
        return point_add_cached(acc, asel, need_t=False)

    return lax.fori_loop(1, WINDOWS256, body, acc)
