"""Batched twisted-Edwards (ed25519) group operations in JAX for TPU.

Points are extended homogeneous coordinates ``(X, Y, Z, T)`` with
``x = X/Z, y = Y/Z, x*y = T/Z``; each coordinate is a GF(2^255-19) limb
array of shape ``(20, *batch)`` (see :mod:`stellar_tpu.ops.field25519`).
All formulas are the *complete* RFC 8032 / "hwcd" unified formulas (valid
for every pair of curve points, including identity and equal inputs), so
there is no data-dependent control flow anywhere — everything maps to
straight-line VPU code under ``jit``.

This is the group layer under the batch signature verifier
(:mod:`stellar_tpu.ops.verify`), the TPU-native replacement for the
reference's libsodium ge25519 layer (reference: the verify path behind
``PubKeyUtils::verifySig``, ``src/crypto/SecretKey.cpp:435-468``).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from stellar_tpu.ops import field25519 as fe
from stellar_tpu.crypto import ed25519_ref as ref

__all__ = [
    "identity", "point_add", "point_add_cached", "point_double",
    "to_cached", "decompress", "compress_equals",
    "negate", "select_point", "table_select", "base_table", "D_LIMBS",
    "D2_LIMBS", "SQRTM1_LIMBS", "unpack255",
]

# Curve constants as canonical limb vectors (host numpy, broadcast at trace).
D_LIMBS = fe.from_int(ref.D)
D2_LIMBS = fe.from_int(2 * ref.D % ref.P)
SQRTM1_LIMBS = fe.from_int(ref.SQRT_M1)


def _const(limbs: np.ndarray, batch_shape):
    c = jnp.asarray(limbs).reshape((fe.NLIMBS,) + (1,) * len(batch_shape))
    return jnp.broadcast_to(c, (fe.NLIMBS,) + tuple(batch_shape))


def identity(batch_shape=()):
    z = fe.zeros(batch_shape)
    one = _const(fe.from_int(1), batch_shape)
    return (z, one, one, z)


def negate(p):
    x, y, z, t = p
    return (fe.neg(x), y, z, fe.neg(t))


def _mul4(ls, rs):
    """Four field multiplies fused into ONE stacked multiply over a
    (20, 4, *batch) operand. The hot loop is bound by per-op overhead on
    small (20, batch) tensors, not FLOPs — quartering the op count by
    widening the batch axis is the single biggest lever on TPU."""
    o = fe.mul(jnp.stack(ls, axis=1), jnp.stack(rs, axis=1))
    return o[:, 0], o[:, 1], o[:, 2], o[:, 3]


def to_cached(p):
    """Extended point -> ref10 ``ge_cached`` form (Y+X, Y-X, Z, 2d*T):
    the representation table entries are stored in, making every
    window add exactly two fused multiplies."""
    x, y, z, t = p
    d2 = _const(D2_LIMBS, t.shape[1:])
    return (fe.add(y, x), fe.sub(y, x), z, fe.mul(t, d2))


def point_add_cached(p, q_cached):
    """p (extended) + q (cached) — complete unified addition as two
    fused 4-way multiplies (reference: libsodium ge25519_add)."""
    x1, y1, z1, t1 = p
    ypx2, ymx2, z2, t2d2 = q_cached
    a, b, c, dd = _mul4((fe.sub(y1, x1), fe.add(y1, x1), t1, z1),
                        (ymx2, ypx2, t2d2, z2))
    dd = fe.add(dd, dd)
    e = fe.sub(b, a)
    f = fe.sub(dd, c)
    g = fe.add(dd, c)
    h = fe.add(b, a)
    return _mul4((e, g, f, e), (f, h, g, h))


def point_add(p, q):
    """Complete unified addition of two extended points."""
    return point_add_cached(p, to_cached(q))


def point_double(p):
    """Dedicated doubling; one fused squaring + one fused multiply."""
    x1, y1, z1, _ = p
    s = fe.sqr(jnp.stack([x1, y1, z1, fe.add(x1, y1)], axis=1))
    a, b, zz, xysq = s[:, 0], s[:, 1], s[:, 2], s[:, 3]
    c = fe.add(zz, zz)
    h = fe.add(a, b)
    e = fe.sub(h, xysq)
    g = fe.sub(a, b)
    f = fe.add(c, g)
    return _mul4((e, g, f, e), (f, h, g, h))


def select_point(cond, p, q):
    """Per-batch-element point select: cond (batch,) -> p where true."""
    return tuple(fe.select(cond, a, b) for a, b in zip(p, q))


def unpack255(b):
    """(batch, 32) uint8 little-endian -> ((20, batch) limbs of low 255
    bits, (batch,) int32 top bit). Limbs are strict 13-bit digits."""
    nbatch = b.shape[0]
    bits = ((b[:, :, None].astype(jnp.int32)
             >> jnp.arange(8, dtype=jnp.int32)) & 1)
    bits = bits.reshape(nbatch, 256)
    sign = bits[:, 255]
    bits = bits * (jnp.arange(256) != 255).astype(jnp.int32)
    bits = jnp.pad(bits, ((0, 0), (0, 260 - 256)))
    weights = (1 << jnp.arange(fe.BITS, dtype=jnp.int32))
    limbs = (bits.reshape(nbatch, fe.NLIMBS, fe.BITS) * weights).sum(-1)
    return limbs.T, sign


def decompress(a_bytes):
    """Batched ge25519_frombytes: (batch, 32) uint8 -> (ok, point).

    Mirrors libsodium's frombytes math (y taken mod p implicitly; candidate
    square root via the (p-5)/8 exponent, corrected by sqrt(-1); "negative
    zero" x==0 with sign=1 rejected). Canonicity/small-order policy checks
    live host-side in :mod:`stellar_tpu.crypto.batch_verifier`, matching the
    split in the reference (`crypto/SecretKey.cpp:435-468`).
    """
    y, sign = unpack255(a_bytes)
    batch = y.shape[1:]
    one = _const(fe.from_int(1), batch)
    y2 = fe.sqr(y)
    u = fe.sub(y2, one)
    v = fe.add(fe.mul(y2, _const(D_LIMBS, batch)), one)
    v3 = fe.mul(fe.sqr(v), v)
    v7 = fe.mul(fe.sqr(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow22523(fe.mul(u, v7)))
    vxx = fe.canon(fe.mul(fe.sqr(x), v))
    u_c = fe.canon(u)
    negu_c = fe.canon(fe.neg(u))
    ok_direct = (vxx == u_c).all(axis=0)
    ok_twist = (vxx == negu_c).all(axis=0)
    x = fe.select(ok_twist, fe.mul(x, _const(SQRTM1_LIMBS, batch)), x)
    ok = ok_direct | ok_twist
    x_c = fe.canon(x)
    x_zero = (x_c == 0).all(axis=0)
    ok = ok & ~(x_zero & (sign == 1))
    flip = (x_c[0] & 1) != sign
    x = fe.select(flip, fe.neg(x), x)
    t = fe.mul(x, y)
    return ok, (x, y, _const(fe.from_int(1), batch), t)


def compress_equals(p, r_bytes):
    """encode(p) == r_bytes, batched, without materializing bytes.

    The encoding of p is always canonical, and ``unpack255`` yields the
    exact digits of r's 255-bit integer, so canonical-limb equality plus
    sign-bit equality is exactly libsodium's bytewise crypto_verify_32.
    """
    x, y, z, _ = p
    zinv = fe.inv(z)
    xa = fe.canon(fe.mul(x, zinv))
    ya = fe.canon(fe.mul(y, zinv))
    ry, rsign = unpack255(r_bytes)
    return ((ya == ry).all(axis=0)) & ((xa[0] & 1) == rsign)


def table_select(table, digit):
    """table (16, 4, 20, batch), digit (batch,) int32 -> cached point.

    One-hot multiply-accumulate — branchless, constant-shape, VPU-friendly
    (a gather would lower to a serial dynamic-slice loop on TPU).
    """
    onehot = (jnp.arange(16, dtype=jnp.int32)[:, None]
              == digit[None, :]).astype(jnp.int32)
    sel = (table * onehot[:, None, None, :]).sum(axis=0)
    return (sel[0], sel[1], sel[2], sel[3])


def _base_multiples() -> np.ndarray:
    """Host-precomputed v*B for v in 0..15 in CACHED form (y+x, y-x, 1,
    2d*x*y) canonical limbs, shape (16, 4, 20) int32."""
    out = np.zeros((16, 4, fe.NLIMBS), dtype=np.int32)
    for v in range(16):
        pt = ref.point_mul(v, ref.BASE)
        zinv = ref._inv(pt[2])
        x = pt[0] * zinv % ref.P
        y = pt[1] * zinv % ref.P
        out[v, 0] = fe.from_int((y + x) % ref.P)
        out[v, 1] = fe.from_int((y - x) % ref.P)
        out[v, 2] = fe.from_int(1)
        out[v, 3] = fe.from_int(2 * ref.D * x * y % ref.P)
    return out


_BASE_TABLE = _base_multiples()


def base_table(batch_shape):
    """(16, 4, 20, *batch) broadcast constant cached table of v*B."""
    t = jnp.asarray(_BASE_TABLE).reshape(
        (16, 4, fe.NLIMBS) + (1,) * len(batch_shape))
    return jnp.broadcast_to(t, (16, 4, fe.NLIMBS) + tuple(batch_shape))


def build_point_table(p):
    """Per-batch cached table v*p for v in 0..15 -> (16, 4, 20, batch)."""
    cp = to_cached(p)
    entries = [to_cached(identity(p[0].shape[1:])), cp]
    plain = p
    for v in range(2, 16):
        plain = point_add_cached(plain, cp)
        entries.append(to_cached(plain))
    return jnp.stack([jnp.stack(e) for e in entries])


def double_scalarmult(s_digits, h_digits, a_neg):
    """R' = s*B + h*a_neg via Strauss-Shamir with 4-bit windows.

    s_digits, h_digits: (64, batch) int32 radix-16 digits, most significant
    first. a_neg: extended point (the verifier passes -A). 252 shared
    doublings + 128 cached-table adds, all under one fori_loop — the hot
    loop of the whole framework.
    """
    batch = a_neg[0].shape[1:]
    tab_a = build_point_table(a_neg)
    tab_b = base_table(batch)

    def body(j, acc):
        for _ in range(4):
            acc = point_double(acc)
        sd = lax.dynamic_index_in_dim(s_digits, j, 0, keepdims=False)
        hd = lax.dynamic_index_in_dim(h_digits, j, 0, keepdims=False)
        acc = point_add_cached(acc, table_select(tab_b, sd))
        acc = point_add_cached(acc, table_select(tab_a, hd))
        return acc

    return lax.fori_loop(0, 64, body, identity(batch))
