"""GF(2^255-19) arithmetic in JAX, designed for the TPU VPU.

Representation: a field element is an int32 array of shape ``(20, ...)`` —
limb-major, radix 2^13 (limb i has weight 2^(13*i)), batch dims trailing so
the batch rides the 128-wide vector lanes. Elements are kept in a *loose*
redundant form: every limb in [0, LOOSE_MAX], value congruent mod p but not
unique. Only :func:`canon` produces the canonical representative in [0, p).

Design notes (why this shape):

* **radix 2^13 / int32** — the TPU VPU has no native 64-bit multiply (int64
  is emulated as 32-bit pairs). With 13-bit limbs a schoolbook product
  coefficient is at most 20 * LOOSE_MAX^2 < 2^31, so the whole multiply
  stays in native int32 — ref10's 25.5-bit-limb/64-bit-accumulator trick
  (libsodium, the impl behind the reference's verify path,
  src/crypto/SecretKey.cpp:435) re-sized for TPU hardware.

* **lazy parallel carries** — instead of a sequential 20-step carry chain
  (which makes long scalar dependency chains XLA compiles and schedules
  badly), carries are propagated with whole-array "rotate-and-fold" steps:
  ``x -> (x & MASK) + shift_down(x >> 13)`` where the carry off the top limb
  re-enters limb 0 scaled by 608 (2^260 ≡ 19*2^5 mod p). Two such steps
  after a multiply bound limbs by ~10k, which is loose-valid. Carries never
  fully normalize — they don't need to until compare/encode time.

All functions are pure, shape-polymorphic in the batch dims, and jittable.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

NLIMBS = 20
BITS = 13
MASK = (1 << BITS) - 1
P = 2**255 - 19
# 2^260 == 2^5 * 2^255 ≡ 19 * 32 (mod p): fold factor for carries off limb 19.
FOLD = 19 * 32  # 608
# Loose limb bound: 20 * LOOSE_MAX^2 must stay < 2^31 (int32).
LOOSE_MAX = 10200
assert NLIMBS * LOOSE_MAX * LOOSE_MAX < 2**31

__all__ = [
    "NLIMBS", "BITS", "MASK", "P", "LOOSE_MAX", "from_int", "to_int",
    "zeros", "add", "sub", "mul", "sqr", "mul_small", "neg", "inv",
    "pow22523", "canon", "eq", "is_zero", "select", "constant",
]


def from_int(x: int) -> np.ndarray:
    """Python int -> normalized limb vector (host-side helper)."""
    x %= P
    return np.array([(x >> (BITS * i)) & MASK for i in range(NLIMBS)],
                    dtype=np.int32)


def constant(x: int, batch_shape=()) -> jnp.ndarray:
    """Broadcast a Python int constant to limb shape (20, *batch_shape)."""
    c = from_int(x).reshape((NLIMBS,) + (1,) * len(batch_shape))
    return jnp.broadcast_to(jnp.asarray(c), (NLIMBS,) + tuple(batch_shape))


def to_int(a) -> np.ndarray:
    """Limb array (20, ...) -> object ndarray of Python ints (test helper)."""
    a = np.asarray(a)
    out = np.zeros(a.shape[1:], dtype=object)
    for i in range(NLIMBS - 1, -1, -1):
        out = out * (1 << BITS) + a[i].astype(object)
    return out


def zeros(batch_shape=()) -> jnp.ndarray:
    return jnp.zeros((NLIMBS,) + tuple(batch_shape), dtype=jnp.int32)


def _carry_step(x):
    """One parallel carry round on a (20, ...) array: every limb keeps its
    low 13 bits and receives the previous limb's overflow; the top limb's
    overflow re-enters limb 0 as * 608. Value mod p is preserved."""
    lo = x & MASK
    hi = x >> BITS
    wrapped = jnp.concatenate([hi[-1:] * FOLD, hi[:-1]], axis=0)
    return lo + wrapped


def add(a, b):
    # limbs <= 2*LOOSE_MAX; one carry round -> <= MASK + 2 + 2*FOLD (loose).
    return _carry_step(a + b)


# Padding for subtraction: digits of 64*p, borrow-adjusted so every limb is
# >= 16382 except limb 0 (>= 15168) — all >= LOOSE_MAX, making a + PAD - b
# non-negative limbwise for loose a, b. (Values are < 2^260.4 <= 64p.)
def _sub_pad():
    v = 64 * P
    d = [(v >> (BITS * i)) & MASK for i in range(NLIMBS - 1)]
    d.append(v >> (BITS * (NLIMBS - 1)))  # top digit (14 bits)
    t = [d[0] + (1 << BITS)]
    for i in range(1, NLIMBS - 1):
        t.append(d[i] + (1 << BITS) - 1)
    t.append(d[NLIMBS - 1] - 1)
    assert sum(ti << (BITS * i) for i, ti in enumerate(t)) == v
    assert all(ti >= LOOSE_MAX for ti in t)
    return np.array(t, dtype=np.int32)


_SUB_PAD = _sub_pad()


def sub(a, b):
    pad = jnp.asarray(_SUB_PAD.reshape((NLIMBS,) + (1,) * (a.ndim - 1)))
    # limbs <= LOOSE_MAX + 16383 ~ 26.6k; one round -> <= MASK + 4 + 3*FOLD.
    return _carry_step(a + pad - b)


def neg(a):
    return sub(zeros(a.shape[1:]), a)


def mul(a, b):
    """Schoolbook 20x20 -> 39-coefficient product, vectorized as 20
    statically shifted row-adds; inputs loose (limbs <= LOOSE_MAX).

    Every shift is a compile-time-constant ``jnp.pad`` so the whole product
    is one XLA elementwise fusion (the round-1 ``dynamic_update_slice``
    formulation lowered to ~20 unfused kernels per multiply, which made
    this op launch-bound on TPU)."""
    batch = a.shape[1:]
    pad_rest = ((0, 0),) * len(batch)
    # rows[i] = a[i] * b placed at limb offset i inside 39 coefficients.
    acc = None
    for i in range(NLIMBS):
        row = a[i][None] * b  # (20, ...) — products <= LOOSE_MAX^2 ~ 1.04e8
        shifted = jnp.pad(row, ((i, NLIMBS - 1 - i),) + pad_rest)
        acc = shifted if acc is None else acc + shifted
    # acc coefficients <= 20 * LOOSE_MAX^2 < 2^31.
    # Carry round over 39 coeffs; the top overflow becomes coeff 39.
    lo = acc & MASK
    hi = acc >> BITS
    shifted = jnp.concatenate(
        [jnp.zeros((1,) + batch, jnp.int32), hi[:-1]], axis=0)
    c40_low = lo + shifted  # coeffs 0..38, <= MASK + 254k
    c39 = hi[-1:]  # coeff 39, <= 254k
    # Fold coeffs 20..39 onto 0..19: 2^(13*(20+j)) ≡ 608 * 2^(13*j) (mod p).
    high = jnp.concatenate([c40_low[NLIMBS:], c39], axis=0)  # (20, ...)
    low = c40_low[:NLIMBS] + FOLD * high  # <= 262k + 608*262k… no:
    # high <= 262k only for the first row; bound: high <= MASK+254k+254k…
    # empirical worst-case bound is checked in tests/test_field25519.py.
    return _carry_step(_carry_step(low))


def sqr(a):
    """Dedicated squaring: the off-diagonal products a_i*a_j (i<j) appear
    twice in the schoolbook sum, so compute them once against a pre-doubled
    operand — ~210 limb products instead of 400. Same worst-case coefficient
    bound as :func:`mul` (20 terms of <= LOOSE_MAX^2 each)."""
    batch = a.shape[1:]
    pad_rest = ((0, 0),) * len(batch)
    a2 = a + a  # limbs <= 2*LOOSE_MAX; products vs a <= 2*LOOSE_MAX^2
    acc = None
    for i in range(NLIMBS):
        # diagonal term a_i^2 at offset 2i, doubled cross terms a_i*a_j
        # (j > i) at offsets i+j.
        row = jnp.concatenate([a[i][None] * a[i][None], a[i][None] * a2[i + 1:]],
                              axis=0)  # (20-i, ...)
        shifted = jnp.pad(row, ((2 * i, NLIMBS - 1 - i),) + pad_rest)
        acc = shifted if acc is None else acc + shifted
    lo = acc & MASK
    hi = acc >> BITS
    shifted = jnp.concatenate(
        [jnp.zeros((1,) + batch, jnp.int32), hi[:-1]], axis=0)
    c40_low = lo + shifted
    c39 = hi[-1:]
    high = jnp.concatenate([c40_low[NLIMBS:], c39], axis=0)
    low = c40_low[:NLIMBS] + FOLD * high
    return _carry_step(_carry_step(low))


def mul_small(a, k: int):
    """Multiply by a small non-negative int constant; k * LOOSE_MAX must be
    << 2^31 (k <= 2^17 is safe)."""
    return _carry_step(_carry_step(_carry_step(a * k)))


def _pow2k(a, k):
    """a^(2^k) by repeated squaring (fori_loop keeps the HLO graph small)."""
    if k <= 2:
        for _ in range(k):
            a = sqr(a)
        return a
    return lax.fori_loop(0, k, lambda _, x: sqr(x), a, unroll=False)


def _pow22501(z):
    """Shared addition chain (ref10 layout): returns (z^(2^250-1), z^11)."""
    t0 = sqr(z)
    t1 = _pow2k(t0, 2)  # z^8
    t1 = mul(z, t1)  # z^9
    t0 = mul(t0, t1)  # z^11
    t2 = sqr(t0)  # z^22
    t1 = mul(t1, t2)  # z^31 = z^(2^5-1)
    t2 = _pow2k(t1, 5)
    t1 = mul(t2, t1)  # z^(2^10-1)
    t2 = _pow2k(t1, 10)
    t2 = mul(t2, t1)  # z^(2^20-1)
    t3 = _pow2k(t2, 20)
    t2 = mul(t3, t2)  # z^(2^40-1)
    t2 = _pow2k(t2, 10)
    t1 = mul(t2, t1)  # z^(2^50-1)
    t2 = _pow2k(t1, 50)
    t2 = mul(t2, t1)  # z^(2^100-1)
    t3 = _pow2k(t2, 100)
    t2 = mul(t3, t2)  # z^(2^200-1)
    t2 = _pow2k(t2, 50)
    t1 = mul(t2, t1)  # z^(2^250-1)
    return t1, t0


def inv(z):
    """z^(p-2) — field inverse (0 maps to 0)."""
    t1, t0 = _pow22501(z)
    t1 = _pow2k(t1, 5)
    return mul(t1, t0)  # z^(2^255-21)


def pow22523(z):
    """z^((p-5)/8) = z^(2^252-3) — the sqrt-ratio exponent."""
    t1, _ = _pow22501(z)
    t1 = _pow2k(t1, 2)
    return mul(z, t1)


def _strict_carry(a):
    """Sequential full carry -> all limbs < 2^13, value < 2^260. Only used
    inside canon (once per encode), so the 20-step chain is acceptable."""
    limbs = [a[i] for i in range(NLIMBS)]
    carry = None
    out = []
    for i in range(NLIMBS):
        v = limbs[i] if carry is None else limbs[i] + carry
        carry = v >> BITS
        out.append(v & MASK)
    out[0] = out[0] + carry * FOLD  # tiny
    carry2 = None
    out2 = []
    for i in range(NLIMBS):
        v = out[i] if carry2 is None else out[i] + carry2
        carry2 = v >> BITS
        out2.append(v & MASK)
    return out2  # carry2 provably 0


def canon(a):
    """Fully reduce a loose element to its canonical value in [0, p)."""
    limbs = _strict_carry(a)
    a = jnp.stack(limbs)
    # Fold bits >= 255 twice: value < 2^260 -> < 2^255 + eps -> < 2p.
    for _ in range(2):
        hi = a[NLIMBS - 1] >> 8
        limbs = [a[i] for i in range(NLIMBS)]
        limbs[NLIMBS - 1] = a[NLIMBS - 1] & 0xFF
        limbs[0] = limbs[0] + 19 * hi
        out = _strict_carry(jnp.stack(limbs))
        a = jnp.stack(out)
    # Conditional subtract p (value now < 2p).
    pd = np.array([(P >> (BITS * i)) & MASK for i in range(NLIMBS)],
                  dtype=np.int32)  # raw digits of p (from_int would reduce!)
    pd_b = pd.reshape((NLIMBS,) + (1,) * (a.ndim - 1))
    t = []
    borrow = None
    for i in range(NLIMBS):
        v = a[i] - pd_b[i] if borrow is None else a[i] - pd_b[i] - borrow
        borrow = (v >> BITS) & 1  # 1 iff negative
        t.append(v & MASK)
    keep = (1 - borrow) == 1  # no final borrow => a >= p => keep subtracted
    out = [jnp.where(keep, t[i], a[i]) for i in range(NLIMBS)]
    return jnp.stack(out)


def eq(a, b):
    """Canonical equality -> bool array of batch shape."""
    return (canon(a) == canon(b)).all(axis=0)


def is_zero(a):
    return (canon(a) == 0).all(axis=0)


def select(cond, a, b):
    """cond: bool batch-shaped; picks a where true else b, limbwise."""
    return jnp.where(jnp.asarray(cond)[None], a, b)
