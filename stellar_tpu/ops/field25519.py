"""GF(2^255-19) arithmetic in JAX, designed for the TPU VPU.

Representation: a field element is an int32 array of shape ``(20, ...)`` —
limb-major, radix 2^13 (limb i has weight 2^(13*i)), batch dims trailing so
the batch rides the 128-wide vector lanes. Elements are kept in a *loose*
redundant form: every limb in [0, LOOSE_MAX], value congruent mod p but not
unique. Only :func:`canon` produces the canonical representative in [0, p).

Design notes (why this shape):

* **radix 2^13 / int32** — the TPU VPU has no native 64-bit multiply (int64
  is emulated as 32-bit pairs). With 13-bit limbs a schoolbook product
  coefficient is at most 20 * LOOSE_MAX^2 < 2^31, so the whole multiply
  stays in native int32 — ref10's 25.5-bit-limb/64-bit-accumulator trick
  (libsodium, the impl behind the reference's verify path,
  src/crypto/SecretKey.cpp:435) re-sized for TPU hardware.

* **lazy parallel carries** — instead of a sequential 20-step carry chain
  (which makes long scalar dependency chains XLA compiles and schedules
  badly), carries are propagated with whole-array "rotate-and-fold" steps:
  ``x -> (x & MASK) + shift_down(x >> 13)`` where the carry off the top limb
  re-enters limb 0 scaled by 608 (2^260 ≡ 19*2^5 mod p). Two such steps
  after a multiply bound limbs by ~10k, which is loose-valid. Carries never
  fully normalize — they don't need to until compare/encode time.

All functions are pure, shape-polymorphic in the batch dims, and jittable.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

NLIMBS = 20
BITS = 13
MASK = (1 << BITS) - 1
P = 2**255 - 19
# 2^260 == 2^5 * 2^255 ≡ 19 * 32 (mod p): fold factor for carries off limb 19.
FOLD = 19 * 32  # 608
# Loose limb bound: 20 * LOOSE_MAX^2 must stay < 2^31 (int32).
LOOSE_MAX = 10200
assert NLIMBS * LOOSE_MAX * LOOSE_MAX < 2**31

__all__ = [
    "NLIMBS", "BITS", "MASK", "P", "LOOSE_MAX", "from_int", "to_int",
    "zeros", "add", "sub", "mul", "sqr", "mul_small", "neg", "inv",
    "inv_scan", "batch_inv", "pow22523", "canon", "eq", "is_zero",
    "select", "constant",
]


def from_int(x: int) -> np.ndarray:
    """Python int -> normalized limb vector (host-side helper)."""
    x %= P
    return np.array([(x >> (BITS * i)) & MASK for i in range(NLIMBS)],
                    dtype=np.int32)


def constant(x: int, batch_shape=()) -> jnp.ndarray:
    """Broadcast a Python int constant to limb shape (20, *batch_shape)."""
    c = from_int(x).reshape((NLIMBS,) + (1,) * len(batch_shape))
    return jnp.broadcast_to(jnp.asarray(c), (NLIMBS,) + tuple(batch_shape))


def to_int(a) -> np.ndarray:
    """Limb array (20, ...) -> object ndarray of Python ints (test helper)."""
    a = np.asarray(a)
    out = np.zeros(a.shape[1:], dtype=object)
    for i in range(NLIMBS - 1, -1, -1):
        out = out * (1 << BITS) + a[i].astype(object)
    return out


def zeros(batch_shape=()) -> jnp.ndarray:
    return jnp.zeros((NLIMBS,) + tuple(batch_shape), dtype=jnp.int32)


def _fold608(h):
    """h * 608 strength-reduced to shifts: 608 = 2^9 + 2^6 + 2^5.
    Value-exact for the non-negative loose-form operands every carry
    fold sees (proven per-equation by the interval prover), and it
    keeps the fold off the multiply units — the fold rode EVERY carry
    round of EVERY field op, so as plain multiplies it accounted for
    ~4% of the dsm stage's executed MAC volume and ~190 static multiply
    equations (see the PR 13 ledger in docs/kernel_design.md §3)."""
    return (h << 9) + (h << 6) + (h << 5)


def _carry_step(x):
    """One parallel carry round on a (20, ...) array: every limb keeps its
    low 13 bits and receives the previous limb's overflow; the top limb's
    overflow re-enters limb 0 as * 608. Value mod p is preserved."""
    lo = x & MASK
    hi = x >> BITS
    wrapped = jnp.concatenate([_fold608(hi[-1:]), hi[:-1]], axis=0)
    return lo + wrapped


def add(a, b):
    # limbs <= 2*LOOSE_MAX; one carry round -> <= MASK + 2 + 2*FOLD (loose).
    return _carry_step(a + b)


# Padding for subtraction: digits of 64*p, borrow-adjusted so every limb is
# >= 16382 except limb 0 (>= 15168) — all >= LOOSE_MAX, making a + PAD - b
# non-negative limbwise for loose a, b. (Values are < 2^260.4 <= 64p.)
def _sub_pad():
    v = 64 * P
    d = [(v >> (BITS * i)) & MASK for i in range(NLIMBS - 1)]
    d.append(v >> (BITS * (NLIMBS - 1)))  # top digit (14 bits)
    t = [d[0] + (1 << BITS)]
    for i in range(1, NLIMBS - 1):
        t.append(d[i] + (1 << BITS) - 1)
    t.append(d[NLIMBS - 1] - 1)
    assert sum(ti << (BITS * i) for i, ti in enumerate(t)) == v
    assert all(ti >= LOOSE_MAX for ti in t)
    return np.array(t, dtype=np.int32)


_SUB_PAD = _sub_pad()


def sub(a, b):
    pad = jnp.asarray(_SUB_PAD.reshape((NLIMBS,) + (1,) * (a.ndim - 1)))
    # limbs <= LOOSE_MAX + 16383 ~ 26.6k; one round -> <= MASK + 4 + 3*FOLD.
    return _carry_step(a + pad - b)


def neg(a):
    return sub(zeros(a.shape[1:]), a)


def mul(a, b):
    """Schoolbook 20x20 -> 39-coefficient product, vectorized as 20
    statically shifted row-adds; inputs loose (limbs <= LOOSE_MAX).

    Every shift is a compile-time-constant ``jnp.pad`` so the whole product
    is one XLA elementwise fusion (the round-1 ``dynamic_update_slice``
    formulation lowered to ~20 unfused kernels per multiply, which made
    this op launch-bound on TPU)."""
    batch = a.shape[1:]
    pad_rest = ((0, 0),) * len(batch)
    # rows[i] = a[i] * b placed at limb offset i inside 39 coefficients.
    acc = None
    for i in range(NLIMBS):
        row = a[i][None] * b  # (20, ...) — products <= LOOSE_MAX^2 ~ 1.04e8
        shifted = jnp.pad(row, ((i, NLIMBS - 1 - i),) + pad_rest)
        acc = shifted if acc is None else acc + shifted
    # acc coefficients <= 20 * LOOSE_MAX^2 < 2^31.
    # Carry round over 39 coeffs; the top overflow becomes coeff 39.
    lo = acc & MASK
    hi = acc >> BITS
    shifted = jnp.concatenate(
        [jnp.zeros((1,) + batch, jnp.int32), hi[:-1]], axis=0)
    c40_low = lo + shifted  # coeffs 0..38, <= MASK + 254k
    c39 = hi[-1:]  # coeff 39, <= 254k
    # Fold coeffs 20..39 onto 0..19: 2^(13*(20+j)) ≡ 608 * 2^(13*j) (mod p).
    high = jnp.concatenate([c40_low[NLIMBS:], c39], axis=0)  # (20, ...)
    low = c40_low[:NLIMBS] + _fold608(high)  # <= 262k + 608*262k… no:
    # high <= 262k only for the first row; bound: high <= MASK+254k+254k…
    # empirical worst-case bound is checked in tests/test_field25519.py.
    return _carry_step(_carry_step(low))


def sqr(a):
    """Dedicated squaring: the off-diagonal products a_i*a_j (i<j) appear
    twice in the schoolbook sum, so compute them once against a pre-doubled
    operand — ~210 limb products instead of 400. Same worst-case coefficient
    bound as :func:`mul` (20 terms of <= LOOSE_MAX^2 each)."""
    batch = a.shape[1:]
    pad_rest = ((0, 0),) * len(batch)
    a2 = a + a  # limbs <= 2*LOOSE_MAX; products vs a <= 2*LOOSE_MAX^2
    acc = None
    for i in range(NLIMBS):
        # diagonal term a_i^2 at offset 2i, doubled cross terms a_i*a_j
        # (j > i) at offsets i+j.
        row = jnp.concatenate([a[i][None] * a[i][None], a[i][None] * a2[i + 1:]],
                              axis=0)  # (20-i, ...)
        shifted = jnp.pad(row, ((2 * i, NLIMBS - 1 - i),) + pad_rest)
        acc = shifted if acc is None else acc + shifted
    lo = acc & MASK
    hi = acc >> BITS
    shifted = jnp.concatenate(
        [jnp.zeros((1,) + batch, jnp.int32), hi[:-1]], axis=0)
    c40_low = lo + shifted
    c39 = hi[-1:]
    high = jnp.concatenate([c40_low[NLIMBS:], c39], axis=0)
    low = c40_low[:NLIMBS] + _fold608(high)
    return _carry_step(_carry_step(low))


def mul_small(a, k: int):
    """Multiply by a small non-negative int constant; k * LOOSE_MAX must be
    << 2^31 (k <= 2^17 is safe)."""
    return _carry_step(_carry_step(_carry_step(a * k)))


def _pow2k(a, k):
    """a^(2^k) by repeated squaring (fori_loop keeps the HLO graph small)."""
    if k <= 2:
        for _ in range(k):
            a = sqr(a)
        return a
    return lax.fori_loop(0, k, lambda _, x: sqr(x), a, unroll=False)


def _pow22501(z):
    """Shared addition chain (ref10 layout): returns (z^(2^250-1), z^11)."""
    t0 = sqr(z)
    t1 = _pow2k(t0, 2)  # z^8
    t1 = mul(z, t1)  # z^9
    t0 = mul(t0, t1)  # z^11
    t2 = sqr(t0)  # z^22
    t1 = mul(t1, t2)  # z^31 = z^(2^5-1)
    t2 = _pow2k(t1, 5)
    t1 = mul(t2, t1)  # z^(2^10-1)
    t2 = _pow2k(t1, 10)
    t2 = mul(t2, t1)  # z^(2^20-1)
    t3 = _pow2k(t2, 20)
    t2 = mul(t3, t2)  # z^(2^40-1)
    t2 = _pow2k(t2, 10)
    t1 = mul(t2, t1)  # z^(2^50-1)
    t2 = _pow2k(t1, 50)
    t2 = mul(t2, t1)  # z^(2^100-1)
    t3 = _pow2k(t2, 100)
    t2 = mul(t3, t2)  # z^(2^200-1)
    t2 = _pow2k(t2, 50)
    t1 = mul(t2, t1)  # z^(2^250-1)
    return t1, t0


def inv(z):
    """z^(p-2) — field inverse (0 maps to 0)."""
    t1, t0 = _pow22501(z)
    t1 = _pow2k(t1, 5)
    return mul(t1, t0)  # z^(2^255-21)


def pow22523(z):
    """z^((p-5)/8) = z^(2^252-3) — the sqrt-ratio exponent."""
    t1, _ = _pow22501(z)
    t1 = _pow2k(t1, 2)
    return mul(z, t1)


# Exponent bits of p-2 after the leading 1, most significant first: the
# square-and-multiply schedule of inv_scan (254 iterations, static).
_INV_EXP_BITS = np.array(
    [(P - 2) >> i & 1 for i in range((P - 2).bit_length() - 2, -1, -1)],
    dtype=np.bool_)


def inv_scan(z):
    """z^(p-2) as a SCAN-shaped square-and-multiply (0 maps to 0).

    Same value as :func:`inv`, different cost shape: the ref10 addition
    chain unrolls ~770 multiply equations into the jaxpr (fine when the
    inverse amortizes over a whole stage, ruinous for program size when
    it doesn't), while this is ONE 254-trip ``lax.scan`` over the
    static exponent bits — ~70 multiply equations, at ~2.6x the
    *executed* squaring/multiply volume. Use it where the operand is a
    single (or near-single) element so executed cost is nil and program
    size is what matters: the one true inversion inside
    :func:`batch_inv`."""
    def body(acc, bit):
        acc = sqr(acc)
        return jnp.where(bit, mul(acc, z), acc), None
    out, _ = lax.scan(body, z, jnp.asarray(_INV_EXP_BITS))
    return out


def _roll_batch(x, shift, width):
    """Cyclic left-neighbour roll along the flattened batch axis:
    result[:, b] = x[:, (b - shift) mod width], with a traced ``shift``
    (dynamic_slice over a doubled copy keeps ONE fori body for every
    tree level instead of log2(batch) unrolled ones)."""
    doubled = jnp.concatenate([x, x], axis=1)
    start = jnp.asarray(width, jnp.int32) - shift.astype(jnp.int32)
    return lax.dynamic_slice(
        doubled, (jnp.int32(0), start), (NLIMBS, width))


def _inv_all_lanes(t):
    """Inverse of every lane of ``t`` (NLIMBS, B) paying ONE scalar
    inversion: log2(B)-level cyclic product tree (Montgomery's trick
    across the batch axis). Requires B to be a power of two (the jit
    bucket sizes are); callers pad with multiplicative 1s otherwise.

    Level l of the tree holds, per lane b, the product of the 2^l
    consecutive lanes ending at b (cyclically). Accumulating each level
    rolled by the partial width gives the EXCLUSIVE all-but-self
    product ex[b] = prod_{k != b} t[k] in 2*log2(B) full multiplies;
    the grand product G (level log2(B), any lane) is inverted once with
    :func:`inv_scan`, and inv(t[b]) = inv(G) * ex[b]."""
    width = t.shape[1]
    levels = max(0, int(width - 1).bit_length())
    assert width == 1 << levels or width == 1, width

    def body(l, carry):
        w, ex, shift = carry
        ex = mul(ex, _roll_batch(w, shift, width))
        w = mul(w, _roll_batch(w, jnp.int32(1) << l, width))
        return w, ex, shift + (jnp.int32(1) << l)

    ones = jnp.broadcast_to(
        jnp.asarray(from_int(1)).reshape(NLIMBS, 1), t.shape)
    total, ex, _ = lax.fori_loop(
        0, levels, body, (t, ones, jnp.int32(1)))
    g = lax.slice(total, (0, 0), (NLIMBS, 1))  # every lane holds G
    # mul derives batch shape from its first operand: broadcast the
    # single inverted element across the lanes explicitly
    return mul(jnp.broadcast_to(inv_scan(g), ex.shape), ex)


def batch_inv(z):
    """Elementwise field inverse of ``z`` with shape (20, N, *batch) —
    N independent elements per lane stacked on the fused-multiply axis
    (:func:`stellar_tpu.ops.edwards._mulstack`'s axis) — via
    Montgomery's trick, paying ONE true inversion for the WHOLE call:

      1. prefix-product scan along the N entries (per lane);
      2. cyclic product tree across the flattened batch lanes
         (:func:`_inv_all_lanes`), ending in a single-element
         :func:`inv_scan`;
      3. back-substitution scan along the entries.

    Semantics match per-element :func:`inv` exactly, including
    inv(0) == 0: zero entries are substituted with 1 before the chain
    (one zero would otherwise annihilate every product it touches —
    across LANES here, which would break lane independence) and zeroed
    again afterwards. The substitution triggers only for z ≡ 0 mod p,
    which valid curve points never produce (complete-formula Z is
    nonzero), so on the verify path it is dead code that exists to keep
    garbage lanes from poisoning their neighbours' verdicts."""
    n = z.shape[1]
    batch = z.shape[2:]
    was_zero = is_zero(z)  # (N, *batch) bool
    one = constant(1, z.shape[1:])
    zs = select(was_zero, one, z)
    zmov = jnp.moveaxis(zs, 1, 0)  # (N, 20, *batch)

    def prefix(c, zi):
        c2 = mul(c, zi)
        return c2, c2

    total, prefixes = lax.scan(prefix, zmov[0], zmov[1:])
    prefixes = jnp.concatenate([zmov[:1], prefixes], axis=0)

    # ONE inversion for all lanes: flatten batch, pad to a power of two
    # with 1s (jit buckets are powers of two, so the pad is usually
    # width zero and traced away).
    nbatch = 1
    for d in batch:
        nbatch *= int(d)
    flat = total.reshape(NLIMBS, nbatch)
    width = 1 if nbatch <= 1 else 1 << (nbatch - 1).bit_length()
    if width != nbatch:
        pad1 = jnp.broadcast_to(
            jnp.asarray(from_int(1)).reshape(NLIMBS, 1),
            (NLIMBS, width - nbatch))
        flat = jnp.concatenate([flat, pad1], axis=1)
    tinv = _inv_all_lanes(flat)[:, :nbatch].reshape(total.shape)

    def backsub(u, xs):
        zi, cprev = xs
        inv_i = mul(u, cprev)
        return mul(u, zi), inv_i

    u_fin, invs_rev = lax.scan(
        backsub, tinv, (zmov[1:][::-1], prefixes[:-1][::-1]))
    invs = jnp.concatenate([u_fin[None], invs_rev[::-1]], axis=0)
    out = jnp.moveaxis(invs, 0, 1)
    return select(was_zero, zeros(z.shape[1:]), out)


def _strict_carry(a):
    """Sequential full carry -> all limbs < 2^13, value < 2^260. Only used
    inside canon (once per encode), so the 20-step chain is acceptable."""
    limbs = [a[i] for i in range(NLIMBS)]
    carry = None
    out = []
    for i in range(NLIMBS):
        v = limbs[i] if carry is None else limbs[i] + carry
        carry = v >> BITS
        out.append(v & MASK)
    out[0] = out[0] + carry * FOLD  # tiny
    carry2 = None
    out2 = []
    for i in range(NLIMBS):
        v = out[i] if carry2 is None else out[i] + carry2
        carry2 = v >> BITS
        out2.append(v & MASK)
    return out2  # carry2 provably 0


def canon(a):
    """Fully reduce a loose element to its canonical value in [0, p)."""
    limbs = _strict_carry(a)
    a = jnp.stack(limbs)
    # Fold bits >= 255 twice: value < 2^260 -> < 2^255 + eps -> < 2p.
    for _ in range(2):
        hi = a[NLIMBS - 1] >> 8
        limbs = [a[i] for i in range(NLIMBS)]
        limbs[NLIMBS - 1] = a[NLIMBS - 1] & 0xFF
        limbs[0] = limbs[0] + 19 * hi
        out = _strict_carry(jnp.stack(limbs))
        a = jnp.stack(out)
    # Conditional subtract p (value now < 2p).
    pd = np.array([(P >> (BITS * i)) & MASK for i in range(NLIMBS)],
                  dtype=np.int32)  # raw digits of p (from_int would reduce!)
    pd_b = pd.reshape((NLIMBS,) + (1,) * (a.ndim - 1))
    t = []
    borrow = None
    for i in range(NLIMBS):
        v = a[i] - pd_b[i] if borrow is None else a[i] - pd_b[i] - borrow
        borrow = (v >> BITS) & 1  # 1 iff negative
        t.append(v & MASK)
    keep = (1 - borrow) == 1  # no final borrow => a >= p => keep subtracted
    out = [jnp.where(keep, t[i], a[i]) for i in range(NLIMBS)]
    return jnp.stack(out)


def eq(a, b):
    """Canonical equality -> bool array of batch shape."""
    return (canon(a) == canon(b)).all(axis=0)


def is_zero(a):
    return (canon(a) == 0).all(axis=0)


def select(cond, a, b):
    """cond: bool batch-shaped; picks a where true else b, limbwise."""
    return jnp.where(jnp.asarray(cond)[None], a, b)
