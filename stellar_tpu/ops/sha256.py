"""Batched SHA-256 kernel — workload #2 of the batch-dispatch engine.

FIPS 180-4 SHA-256 over a batch of independent messages, one message
per lane: the device-side half of
:class:`stellar_tpu.crypto.batch_hasher.BatchHasher`. Bucket-list /
TxSet / ledger-header hashing in the reference is thousands of small
INDEPENDENT digests (content hash per tx frame, header hash per
replayed ledger, level hash per bucket level) — embarrassingly
parallel across messages even though each message's compression chain
is sequential.

Design (``docs/kernel_design.md`` §"SHA-256 kernel"):

* **uint32 lanes, batch trailing.** All working values are uint32 with
  the batch on the trailing axis, mapping each of the 8 state words /
  64 schedule words to a (batch,)-wide vector op — the same lane
  layout as the verify kernel's limbs.
* **masked half-word adds.** TPU int32/uint32 addition wraps silently
  — exactly what the overflow prover (:mod:`stellar_tpu.analysis`)
  exists to reject, and this process runs jax with x64 DISABLED, so a
  widening int64 add isn't even representable (it would silently
  truncate back to 32 bits — worse than the wrap it hides; on real
  TPUs int64 is 2x32 emulation anyway). Every mod-2^32 addition is
  therefore an EXPLICIT split-carry add (:func:`_madd`): operands
  split into 16-bit halves, each half-lane summed in uint32 (max 6
  terms < 2^19, proven), the carry propagated once, and the halves
  recombined — the wrap the spec demands, visible to (and certified
  by) the interval prover instead of hidden in hardware.
* **rotations without a not/overflow.** ``rotr(x, n)`` masks the low
  ``n`` bits BEFORE the left shift (``(x >> n) | ((x & (2^n-1)) <<
  (32-n))``), so the shifted operand is provably < 2^32; ``Ch`` uses
  the ``g ^ (e & (f ^ g))`` form so no bitwise-not (whose unsigned
  range the interval domain would have to special-case) appears.
* **host-side packing.** Padding (0x80, zeros, 64-bit BE length) and
  big-endian word packing are cheap byte work done once on the host
  (:func:`pack_messages`); the device receives fixed-shape word
  blocks plus a per-(message, block) ``active`` mask. Messages are
  padded to a fixed block capacity per jit bucket; inactive blocks
  are skipped via ``where`` so every lane runs the same traced
  program (no data-dependent control flow — hot-path lint clean).
* **scanned, not unrolled.** The 64 rounds and the block chain are
  ``lax.scan`` loops with STATIC trip counts (64 and ``max_blocks``),
  so the XLA graph is ONE round body + loop structure — a fully
  unrolled 8-block kernel is ~57k ops and took XLA-CPU >10 min to
  compile. The schedule is computed in-loop from a rolling 16-word
  window carried through the round scan (rounds < 16 select the
  message word instead via a trace-time ``iota < 16`` mask — same
  program every round, the mask decides). Static trips keep both the
  overflow prover (exact scan unrolling, ``max_unroll`` 256) and the
  cost ledger (body ops x trip count) exact.

The kernel's batch axis is LEADING on inputs and output (the engine's
slicing contract); internally everything is transposed batch-trailing
for the vector lanes.
"""

from __future__ import annotations

import numpy as np

__all__ = ["sha256_kernel", "pack_messages", "digest_words_to_bytes",
           "host_digest_words", "blocks_needed", "max_message_bytes",
           "K", "H0"]

# FIPS 180-4 constants: first 32 bits of the fractional parts of the
# cube roots of the first 64 primes (K) / square roots of the first 8
# primes (H0).
K = (
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
)

H0 = (0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
      0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19)

_MASK32 = 0xFFFFFFFF


def blocks_needed(msg_len: int) -> int:
    """64-byte compression blocks after FIPS padding (0x80 + length)."""
    return (msg_len + 9 + 63) // 64


def max_message_bytes(max_blocks: int) -> int:
    """Longest message that fits ``max_blocks`` blocks after padding."""
    return max_blocks * 64 - 9


def _madd(*terms):
    """Masked mod-2^32 add via 16-bit half lanes: each half's sum of
    up to ``len(terms)`` (< 8) values stays < 2^19 — comfortably inside
    uint32, the bound the overflow prover certifies — then one carry
    propagation and a recombine whose pieces are disjoint
    (``hi << 16 <= 2^32 - 2^16``, ``lo < 2^16``), so every
    intermediate provably fits (docs/kernel_design.md)."""
    import jax.numpy as jnp
    half = jnp.uint32(0xFFFF)
    lo = hi = None
    for t in terms:
        if isinstance(t, int):
            tl = jnp.uint32(t & 0xFFFF)
            th = jnp.uint32(t >> 16)
        else:
            tl = t & half
            th = t >> jnp.uint32(16)
        lo = tl if lo is None else lo + tl
        hi = th if hi is None else hi + th
    hi = hi + (lo >> jnp.uint32(16))
    return ((hi & half) << jnp.uint32(16)) + (lo & half)


def _rotr(x, n: int):
    """rotr32 without overflow: the left-shift operand is pre-masked
    to its low ``n`` bits, so ``(x & (2^n-1)) << (32-n)`` is provably
    < 2^32 (no uint32 escape for the interval domain to flag)."""
    import jax.numpy as jnp
    low = jnp.uint32((1 << n) - 1)
    return (x >> jnp.uint32(n)) | ((x & low) << jnp.uint32(32 - n))


def _shr(x, n: int):
    import jax.numpy as jnp
    return x >> jnp.uint32(n)


def _round_step(carry, x):
    """One FIPS 180-4 round as a scan body: schedule expansion from
    the rolling 16-word window + the compression round. ``carry`` is
    ``(state (8, batch), window (16, batch))``; ``x`` is ``(K[i],
    i < 16, padded message word i)``. The first 16 rounds take the
    message word, later rounds the in-loop schedule expansion — the
    SAME traced program every round, a trace-time mask decides."""
    import jax.numpy as jnp
    st, win = carry
    k_i, use_msg, msg_w = x
    # w[i] = w[i-16] + s0(w[i-15]) + w[i-7] + s1(w[i-2]); the window
    # holds w[i-16..i-1] at positions 0..15
    wm15, wm2 = win[1], win[14]
    s0w = _rotr(wm15, 7) ^ _rotr(wm15, 18) ^ _shr(wm15, 3)
    s1w = _rotr(wm2, 17) ^ _rotr(wm2, 19) ^ _shr(wm2, 10)
    w_i = jnp.where(use_msg, msg_w,
                    _madd(win[0], s0w, win[9], s1w))
    a, b, c, d, e, f, g, h = (st[i] for i in range(8))
    s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    # Ch(e,f,g) in the not-free form g ^ (e & (f ^ g))
    ch = g ^ (e & (f ^ g))
    t1 = _madd(h, s1, ch, k_i, w_i)
    s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & b) ^ (a & c) ^ (b & c)
    t2 = _madd(s0, maj)
    new_st = jnp.stack([_madd(t1, t2), a, b, c,
                        _madd(d, t1), e, f, g])
    new_win = jnp.concatenate([win[1:], w_i[None]], axis=0)
    return (new_st, new_win), None


def sha256_kernel(words, active):
    """Batched SHA-256 over padded, word-packed messages.

    Args:
      words: (batch, max_blocks, 16) uint32 — big-endian message words
        per 64-byte block (:func:`pack_messages`); inactive blocks are
        zero-filled and never reach the state.
      active: (batch, max_blocks) bool — True for each message's real
        blocks (always a PREFIX per row).

    Returns:
      (batch, 8) uint32 — the digest as big-endian word VALUES
      (:func:`digest_words_to_bytes` renders the canonical 32 bytes).
      Rows with zero active blocks return the initial state H0 — the
      padding-lane case; the engine slices such rows off.
    """
    import jax
    import jax.numpy as jnp
    batch = words.shape[0]
    # batch-trailing internally: one (batch,)-wide vector per word
    wt = jnp.transpose(words, (1, 2, 0))      # (blocks, 16, batch)
    at = jnp.transpose(active, (1, 0))        # (blocks, batch)
    k_arr = jnp.asarray(np.array(K, dtype=np.uint32))       # (64,)
    use_msg = jnp.arange(64, dtype=jnp.uint32) < jnp.uint32(16)
    zeros48 = jnp.zeros((48, batch), dtype=jnp.uint32)

    def _block_step(state, x):
        w0, act = x                           # (16, batch), (batch,)
        # rounds 16..63 read the zero tail's slot never (use_msg is
        # False there and the window expansion takes over); the pad
        # just gives xs a uniform (64, batch) shape
        msg_padded = jnp.concatenate([w0, zeros48], axis=0)
        (st_new, _win), _ = jax.lax.scan(
            _round_step, (state, w0), (k_arr, use_msg, msg_padded))
        summed = _madd(state, st_new)
        # inactive blocks keep the carried state: every lane runs the
        # same program, the mask decides whether the block counted
        return jnp.where(act[None, :], summed, state), None

    state0 = jnp.tile(
        jnp.asarray(np.array(H0, dtype=np.uint32))[:, None],
        (1, batch))                           # (8, batch)
    state, _ = jax.lax.scan(_block_step, state0, (wt, at))
    return jnp.transpose(state, (1, 0))       # (batch, 8)


# ---------------- host-side packing / decoding ----------------


def pack_messages(msgs, max_blocks: int):
    """FIPS-pad and word-pack ``msgs`` for :func:`sha256_kernel`.

    Returns ``(words, active, fits)``: the kernel inputs plus a bool
    row mask — False where a message needs more than ``max_blocks``
    blocks (such rows must be hashed on the host; their words/active
    rows are zeroed and hash to H0 on device, which the caller
    discards)."""
    n = len(msgs)
    words = np.zeros((n, max_blocks, 16), dtype=np.uint32)
    active = np.zeros((n, max_blocks), dtype=bool)
    fits = np.ones(n, dtype=bool)
    for i, m in enumerate(msgs):
        nb = blocks_needed(len(m))
        if nb > max_blocks:
            fits[i] = False
            continue
        padded = (m + b"\x80" + b"\x00" * ((-(len(m) + 9)) % 64)
                  + (8 * len(m)).to_bytes(8, "big"))
        words[i, :nb] = np.frombuffer(
            padded, dtype=">u4").reshape(nb, 16)
        active[i, :nb] = True
    return words, active, fits


def digest_words_to_bytes(row: np.ndarray) -> bytes:
    """(8,) uint32 word values -> the canonical 32-byte digest."""
    return np.asarray(row, dtype=np.uint32).astype(">u4").tobytes()


def host_digest_words(msgs) -> np.ndarray:
    """hashlib digests as (n, 8) uint32 word values — the differential
    oracle in the kernel's output representation."""
    import hashlib
    out = np.zeros((len(msgs), 8), dtype=np.uint32)
    for i, m in enumerate(msgs):
        out[i] = np.frombuffer(hashlib.sha256(m).digest(),
                               dtype=">u4").astype(np.uint32)
    return out
