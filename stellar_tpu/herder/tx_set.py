"""TxSetFrame: transaction-set construction, hashing, validation, and
apply ordering (reference ``src/herder/TxSetFrame.cpp``).

Pipeline (mirrors the reference's three-stage design):

* ``make_tx_set_from_transactions`` — nominate-time construction: group
  per source account in sequence order, surge-price down to the ledger's
  operation capacity, compute the discounted base fee, emit the
  GeneralizedTransactionSet XDR whose SHA-256 is the set's identity.
* ``TxSetXDRFrame`` — wire form + hash, convertible to an
  ``ApplicableTxSetFrame`` against the current ledger
  (``prepareForApply``).
* ``ApplicableTxSetFrame.check_valid`` — structural checks + per-tx
  ``checkValid``; all ed25519 signatures in the set are first verified
  in ONE TPU batch (``batch_verify_into_cache``), so the per-signer
  logic afterwards only hits the verify cache. This is sig hot path #3
  (``TxSetFrame.cpp:1633``) riding the device.
* ``get_txs_in_apply_order`` — per-account batches shuffled by
  hash XOR setHash (reference ``ApplyTxSorter`` /
  ``sortedForApplySequential``) so apply order is unpredictable but
  deterministic.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from stellar_tpu.crypto.keys import batch_verify_into_cache
from stellar_tpu.crypto.sha import sha256
from stellar_tpu.tx.op_frame import account_key
from stellar_tpu.xdr.ledger import (
    GeneralizedTransactionSet, TransactionPhase, TransactionSetV1,
    TxSetComponent, TxSetComponentType, TxSetComponentTxsMaybeDiscountedFee,
    generalized_tx_set_hash,
)
from stellar_tpu.xdr.runtime import to_bytes
from stellar_tpu.xdr.tx import TransactionEnvelope
from stellar_tpu.xdr.types import SignerKeyType

__all__ = [
    "full_tx_hash", "fee_rate_less_than", "compute_per_op_fee",
    "make_tx_set_from_transactions", "TxSetXDRFrame",
    "ApplicableTxSetFrame", "prefetch_signature_batch",
]


def prefetch_contents_hashes(frames) -> None:
    """Batch-compute and memoize the contents hash (tx id) of every
    frame in one pass through the hash workload
    (``crypto.batch_hasher.hash_many``) — the TxSet half of the
    "bucket-list and TxSet hashing remain serial host SHA-256" item:
    catchup's recorded-results split calls ``contents_hash()`` per
    frame, which this turns into cache hits. Bit-identical (the
    workload's oracle IS hashlib); frames already hashed are skipped."""
    from stellar_tpu.crypto.batch_hasher import hash_many
    todo = [f for f in frames
            if getattr(f, "_hash", None) is None
            and hasattr(f, "contents_preimage")]
    if not todo:
        return
    for f, digest in zip(todo,
                         hash_many([f.contents_preimage()
                                    for f in todo])):
        f._hash = digest


def full_tx_hash(frame) -> bytes:
    """Hash of the whole envelope incl. signatures (reference
    ``getFullHash``) — distinct from the contents hash. Memoized on the
    frame (hot: sorting, apply ordering, canonical-order checks)."""
    h = getattr(frame, "_full_hash", None)
    if h is None:
        eb = getattr(frame, "envelope_bytes", None)
        h = sha256(eb() if eb is not None
                   else to_bytes(TransactionEnvelope, frame.envelope))
        frame._full_hash = h
    return h


def fee_rate_less_than(a, b) -> bool:
    """a bids a strictly lower fee-per-op than b (reference
    ``feeRate3WayCompare``: cross-multiplied, overflow-free)."""
    return a.inclusion_fee() * b.num_operations() < \
        b.inclusion_fee() * a.num_operations()


def compute_per_op_fee(frame) -> int:
    """Inclusion fee per operation, rounded down (current protocol;
    reference ``computePerOpFee``)."""
    return frame.inclusion_fee() // max(1, frame.num_operations())


def _xored(h: bytes, set_hash: bytes) -> bytes:
    return bytes(a ^ b for a, b in zip(h, set_hash))


def _build_account_queues(frames) -> Dict[bytes, List]:
    """Per-source-account tx lists in ascending seq order (reference
    ``TxSetUtils::buildAccountTxQueues``)."""
    queues: Dict[bytes, List] = {}
    for f in frames:
        aid = f.source_account_id().value
        queues.setdefault(aid, []).append(f)
    for q in queues.values():
        q.sort(key=lambda f: f.seq_num)
    return queues


DEX_OP_TYPES = None  # lazily-built frozenset of OperationType values


def _is_dex_tx(frame) -> bool:
    """True when any op trades against the order book (reference
    ``TxSetUtils::hasDexOperations``)."""
    global DEX_OP_TYPES
    if DEX_OP_TYPES is None:
        from stellar_tpu.xdr.tx import OperationType as OT
        DEX_OP_TYPES = frozenset({
            OT.MANAGE_SELL_OFFER, OT.MANAGE_BUY_OFFER,
            OT.CREATE_PASSIVE_SELL_OFFER,
            OT.PATH_PAYMENT_STRICT_RECEIVE,
            OT.PATH_PAYMENT_STRICT_SEND,
        })
    inner = getattr(frame, "inner", frame)
    return any(op.body.arm in DEX_OP_TYPES
               for op in inner.tx.operations)


def make_tx_set_from_transactions(
        frames: Sequence, lcl_header, lcl_hash: bytes,
        soroban_config=None, parallel_soroban: Optional[bool] = None,
        max_dex_ops: Optional[int] = None,
) -> Tuple["ApplicableTxSetFrame", List]:
    """Build a valid (surge-priced) tx set from candidate frames.

    Returns (applicable_frame, excluded_frames). Two phases (reference
    generalized tx sets from protocol 20): the CLASSIC phase is limited
    in operations by ``lcl_header.maxTxSetSize``; the SOROBAN phase in
    transactions by the network config's per-ledger cap. Each phase
    surge-prices independently: when it overflows, lowest-fee-rate
    account tails are trimmed and that phase's discounted base fee
    becomes the lowest included per-op bid (reference
    ``makeTxSetFromTransactions`` + ``SurgePricingPriorityQueue`` +
    ``computeLaneBaseFee``).

    ``parallel_soroban`` (default: ledgerVersion >= 23) emits the
    soroban phase in the PARALLEL representation: footprint-disjoint
    conflict clusters packed into sequential stages (reference
    ``TxSetFrame.cpp:677-903`` building stages/clusters) — the
    TPU-side batch hook: clusters of one stage are data-parallel.
    """
    from stellar_tpu.herder.surge_pricing import (
        SurgePricingLaneConfig, SurgePricingPriorityQueue,
    )
    from stellar_tpu.protocol import SOROBAN_PROTOCOL_VERSION

    classic = [f for f in frames if not f.is_soroban()]
    soroban = [f for f in frames if f.is_soroban()]

    if max_dex_ops is not None:
        # DEX lane (reference MAX_DEX_TX_OPERATIONS_IN_TX_SET): order-
        # book-touching txs additionally cap at lane 1. (The wire form
        # stays single-component: the cap is enforced at construction;
        # per-lane discounted components are not emitted.)
        lane_cfg = SurgePricingLaneConfig(
            [lcl_header.maxTxSetSize, max_dex_ops],
            lane_of=lambda f: 1 if _is_dex_tx(f) else 0)
    else:
        lane_cfg = SurgePricingLaneConfig([lcl_header.maxTxSetSize])
    inc_c, exc_c, full_c = \
        SurgePricingPriorityQueue.most_top_txs_within_limits(
            classic, lane_cfg)
    base_fee_c = SurgePricingPriorityQueue.lane_base_fee(
        inc_c, lcl_header.baseFee, bool(full_c))

    soroban_phase = lcl_header.ledgerVersion >= SOROBAN_PROTOCOL_VERSION
    inc_s: List = []
    excluded = list(exc_c)
    base_fee_s = lcl_header.baseFee
    if soroban_phase:
        if soroban_config is None:
            from stellar_tpu.tx.ops.soroban_ops import (
                default_soroban_config,
            )
            soroban_config = default_soroban_config()
        cap = soroban_config.ledger_max_tx_count
        inc_s, exc_s, full_s = \
            SurgePricingPriorityQueue.most_top_txs_within_limits(
                soroban, SurgePricingLaneConfig(
                    [cap], resources_of=lambda f: 1))
        inc_s, over_cap = _enforce_soroban_ledger_caps(
            inc_s, soroban_config)
        exc_s = list(exc_s) + over_cap
        base_fee_s = SurgePricingPriorityQueue.lane_base_fee(
            inc_s, lcl_header.baseFee, bool(full_s) or bool(over_cap))
        excluded.extend(exc_s)
    else:
        excluded.extend(soroban)

    if parallel_soroban is None:
        from stellar_tpu.protocol import (
            PARALLEL_SOROBAN_PHASE_PROTOCOL_VERSION,
        )
        parallel_soroban = soroban_phase and \
            lcl_header.ledgerVersion >= \
            PARALLEL_SOROBAN_PHASE_PROTOCOL_VERSION
    stages = None
    if parallel_soroban and soroban_phase:
        stages = _build_parallel_stages(inc_s, soroban_config)
    xdr_set = _to_generalized_xdr(inc_c, base_fee_c, inc_s, base_fee_s,
                                  lcl_hash, soroban_phase,
                                  parallel_stages=stages)
    discounts = {id(f): base_fee_c for f in inc_c}
    discounts.update({id(f): base_fee_s for f in inc_s})
    applicable = ApplicableTxSetFrame(xdr_set, inc_c + inc_s, discounts,
                                      soroban_frames=inc_s,
                                      parallel_stages=stages)
    return applicable, excluded


def _parallel_footprint(f) -> Tuple[set, set]:
    """(written_kbs, touched_kbs) for conflict analysis. The source and
    fee-source account keys count as writes: two txs from one account
    mutate its sequence number, so they must serialize in one cluster
    (the reference's per-account soroban queue limit makes this rare,
    but a built set must stay correct without it)."""
    from stellar_tpu.ledger.ledger_txn import key_bytes
    from stellar_tpu.tx.op_frame import account_key
    from stellar_tpu.xdr.types import LedgerKey
    inner = getattr(f, "inner", f)
    fp = inner.tx.ext.value.resources.footprint
    rw = {to_bytes(LedgerKey, k) for k in fp.readWrite}
    ro = {to_bytes(LedgerKey, k) for k in fp.readOnly}
    rw.add(key_bytes(account_key(f.source_account_id())))
    if hasattr(f, "fee_source_id"):
        rw.add(key_bytes(account_key(f.fee_source_id())))
    return rw, rw | ro


def _cluster_order(members: List) -> List:
    """Deterministic in-cluster order: cross-account positions follow
    full-hash order, but each account's own txs fill its positions in
    ascending sequence order — a cluster is a dependency chain, and a
    same-account pair hash-ordered backwards would fail bad-seq at
    validation (code-review r3 finding)."""
    hashed = _sorted_in_hash_order(members)
    by_acct: Dict[bytes, List] = {}
    for f in hashed:
        by_acct.setdefault(f.source_account_id().value, []).append(f)
    for q in by_acct.values():
        q.sort(key=lambda f: f.seq_num)
    taken: Dict[bytes, int] = {}
    out = []
    for f in hashed:
        acct = f.source_account_id().value
        i = taken.get(acct, 0)
        taken[acct] = i + 1
        out.append(by_acct[acct][i])
    return out


def _build_parallel_stages(frames: Sequence, config) -> List[List[List]]:
    """Partition soroban frames into conflict clusters (union-find over
    footprint overlap: a WRITE by one tx against any touch by another
    conflicts) and pack clusters into stages bounded by the network's
    dependent-cluster cap. Deterministic: cluster members and clusters
    order by full tx hash (reference ``TxSetFrame.cpp:677-903``)."""
    if not frames:
        return []
    n = len(frames)
    parent = list(range(n))

    def find(i: int) -> int:
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    def union(i: int, j: int):
        ri, rj = find(i), find(j)
        if ri != rj:
            parent[rj] = ri

    fps = [_parallel_footprint(f) for f in frames]
    touchers: Dict[bytes, List[int]] = {}
    writers: Dict[bytes, List[int]] = {}
    for i, (rw, touched) in enumerate(fps):
        for kb in touched:
            touchers.setdefault(kb, []).append(i)
        for kb in rw:
            writers.setdefault(kb, []).append(i)
    for kb, ws in writers.items():
        anchor = ws[0]
        for i in touchers.get(kb, ()):
            union(anchor, i)

    by_root: Dict[int, List] = {}
    for i in range(n):
        by_root.setdefault(find(i), []).append(frames[i])
    clusters = [_cluster_order(members) for members in by_root.values()]
    clusters.sort(key=lambda cl: full_tx_hash(cl[0]))
    max_clusters = max(1, getattr(config,
                                  "ledger_max_dependent_tx_clusters", 8))
    return [clusters[i:i + max_clusters]
            for i in range(0, len(clusters), max_clusters)]


def _sorted_in_hash_order(frames) -> List:
    # canonical wire order is by FULL envelope hash (reference
    # ``TxSetUtils::sortTxsInHashOrder`` uses getFullHash)
    return sorted(frames, key=full_tx_hash)


def _phase_xdr(frames, base_fee: int):
    comp = TxSetComponent.make(
        TxSetComponentType.TXSET_COMP_TXS_MAYBE_DISCOUNTED_FEE,
        TxSetComponentTxsMaybeDiscountedFee(
            baseFee=base_fee,
            txs=[f.envelope for f in _sorted_in_hash_order(frames)]))
    return TransactionPhase.make(0, [comp] if frames else [])


def _to_generalized_xdr(classic, base_fee_c: int, soroban, base_fee_s: int,
                        lcl_hash: bytes, soroban_phase: bool,
                        parallel_stages=None):
    """Phase 0 = classic, phase 1 = soroban (reference generalized tx
    set layout from protocol 20; single phase before). With
    ``parallel_stages`` the soroban phase is the parallel
    representation (stages of independent clusters)."""
    phases = [_phase_xdr(classic, base_fee_c)]
    if soroban_phase:
        if parallel_stages is not None:
            from stellar_tpu.xdr.ledger import ParallelTxsComponent
            phases.append(TransactionPhase.make(1, ParallelTxsComponent(
                baseFee=base_fee_s,
                executionStages=[
                    [[f.envelope for f in cluster] for cluster in stage]
                    for stage in parallel_stages])))
        else:
            phases.append(_phase_xdr(soroban, base_fee_s))
    return GeneralizedTransactionSet.make(
        1, TransactionSetV1(previousLedgerHash=lcl_hash, phases=phases))


class TxSetXDRFrame:
    """Wire-form tx set: XDR + content hash; parse-on-demand
    (reference ``TxSetXDRFrame``)."""

    def __init__(self, xdr_set):
        self.xdr = xdr_set
        self.hash = generalized_tx_set_hash(xdr_set)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "TxSetXDRFrame":
        from stellar_tpu.xdr.runtime import from_bytes
        return cls(from_bytes(GeneralizedTransactionSet, raw))

    def prepare_for_apply(self, network_id: bytes
                          ) -> Optional["ApplicableTxSetFrame"]:
        """Parse envelopes into frames (reference ``prepareForApply``);
        None on malformed contents."""
        from stellar_tpu.tx.transaction_frame import make_transaction_frame
        try:
            frames = []
            discounts = {}
            soroban_frames = []
            parallel_stages = None
            v1 = self.xdr.value
            for phase_i, phase in enumerate(v1.phases):
                if phase.arm == 1:
                    # parallel Soroban phase: sequential stages of
                    # independent clusters (reference TxSetFrame.h:192-
                    # 254); only valid as the soroban phase
                    if phase_i != 1:
                        return None
                    comp = phase.value
                    parallel_stages = []
                    for stage in comp.executionStages:
                        # structurally invalid: empty stages/clusters
                        # (reference validateParallelComponent) — also
                        # preserves hash-uniqueness of contents
                        if not stage or any(not c for c in stage):
                            return None
                        stage_frames = []
                        for cluster in stage:
                            cluster_frames = []
                            for env in cluster:
                                f = make_transaction_frame(network_id,
                                                           env)
                                frames.append(f)
                                soroban_frames.append(f)
                                discounts[id(f)] = comp.baseFee
                                cluster_frames.append(f)
                            stage_frames.append(cluster_frames)
                        parallel_stages.append(stage_frames)
                    continue
                for comp in phase.value:
                    for env in comp.value.txs:
                        f = make_transaction_frame(network_id, env)
                        frames.append(f)
                        discounts[id(f)] = comp.value.baseFee
                        if phase_i == 1:
                            soroban_frames.append(f)
            return ApplicableTxSetFrame(self.xdr, frames, discounts,
                                        precomputed_hash=self.hash,
                                        soroban_frames=soroban_frames,
                                        parallel_stages=parallel_stages)
        except Exception:
            return None


class ApplicableTxSetFrame:
    """A parsed tx set pinned to the ledger it applies to (reference
    ``ApplicableTxSetFrame``)."""

    def __init__(self, xdr_set, frames: Sequence, discounts: Dict,
                 precomputed_hash: Optional[bytes] = None,
                 soroban_frames: Sequence = (),
                 parallel_stages=None):
        self.xdr = xdr_set
        self.frames = list(frames)
        self._discounts = discounts  # id(frame) -> Optional[baseFee]
        self._soroban_ids = {id(f) for f in soroban_frames}
        # stages -> clusters -> frames when the soroban phase is the
        # parallel representation (protocol 23+ sets); apply is still
        # sequential in this snapshot (reference LedgerManagerImpl
        # .cpp:1619-1689) but stage/cluster order is preserved
        self.parallel_stages = parallel_stages
        self.hash = precomputed_hash if precomputed_hash is not None \
            else generalized_tx_set_hash(xdr_set)

    @property
    def previous_ledger_hash(self) -> bytes:
        return self.xdr.value.previousLedgerHash

    def base_fee_for(self, frame) -> Optional[int]:
        """The discounted base fee this tx applies under (None = bid)."""
        return self._discounts.get(id(frame))

    def size_op(self) -> int:
        """Classic-phase operation count (the maxTxSetSize axis)."""
        return sum(max(1, f.num_operations()) for f in self.frames
                   if id(f) not in self._soroban_ids)

    def size_tx(self) -> int:
        return len(self.frames)

    def soroban_tx_count(self) -> int:
        return len(self._soroban_ids)

    # ---------------- validation ----------------

    def check_valid(self, ltx, lcl_hash: bytes,
                    lower_offset: int = 0, upper_offset: int = 0) -> bool:
        """Full set validation against the current ledger (reference
        ``ApplicableTxSetFrame::checkValid``)."""
        if self.previous_ledger_hash != lcl_hash:
            return False
        header = ltx.header()
        if self.size_op() > header.maxTxSetSize:
            return False
        from stellar_tpu.ledger.ledger_txn import soroban_config_of
        # per-ledger soroban aggregate access caps bind RECEIVED sets
        # too — a peer-built set over the caps must not validate
        # (order-independent sum check; the builder uses the greedy
        # priority walk)
        if _soroban_ledger_caps_exceeded(
                [f for f in self.frames if id(f) in self._soroban_ids],
                soroban_config_of(ltx)):
            return False
        if self.soroban_tx_count() > \
                soroban_config_of(ltx).ledger_max_tx_count:
            return False
        # soroban txs may only ride the soroban phase and vice versa
        for f in self.frames:
            if f.is_soroban() != (id(f) in self._soroban_ids):
                return False
        if self.parallel_stages is not None:
            # the parallel representation is a protocol-23 construct:
            # accepting it earlier would diverge from the network
            # (reference gates on PARALLEL_SOROBAN_PHASE_PROTOCOL_
            # VERSION), and each stage is bounded by the dependent-
            # cluster cap
            from stellar_tpu.protocol import (
                PARALLEL_SOROBAN_PHASE_PROTOCOL_VERSION,
            )
            if header.ledgerVersion < \
                    PARALLEL_SOROBAN_PHASE_PROTOCOL_VERSION:
                return False
            max_clusters = soroban_config_of(
                ltx).ledger_max_dependent_tx_clusters
            if any(len(stage) > max_clusters
                   for stage in self.parallel_stages):
                return False
        # discounted base fee must not be below the protocol minimum
        by_env = {id(f.envelope): full_tx_hash(f) for f in self.frames
                  if not (self.parallel_stages is not None and
                          id(f) in self._soroban_ids)}
        for phase in self.xdr.value.phases:
            if phase.arm == 1:
                bf = phase.value.baseFee
                if bf is not None and bf < header.baseFee:
                    return False
                # clusters are dependency chains, not hash-ordered
                continue
            for comp in phase.value:
                bf = comp.value.baseFee
                if bf is not None and bf < header.baseFee:
                    return False
                # wire order must be canonical (hash-sorted) so the set
                # hash is unique for its contents; envelopes are the
                # frames' own objects, so reuse their memoized hashes
                hashes = [by_env.get(id(e)) or
                          sha256(to_bytes(TransactionEnvelope, e))
                          for e in comp.value.txs]
                if hashes != sorted(hashes):
                    return False
        # every tx must bid at least the component's discounted rate
        # (reference checkValid, TxSetFrame.cpp:1678-1686)
        for f in self.frames:
            bf = self.base_fee_for(f)
            if bf is not None and \
                    f.inclusion_fee() < bf * max(1, f.num_operations()):
                return False
        # keep the collected triples on the set: close_ledger re-seeds
        # from THEM (one cheap batch call that re-verifies anything the
        # bounded cache evicted since validation) instead of re-walking
        # frames and re-loading accounts
        self.sig_triples = prefetch_signature_batch(ltx, self.frames)
        from stellar_tpu.xdr.results import TransactionResultCode as TC
        # per-account chains: each tx validates against its predecessor's
        # seq num (reference ``TxSetUtils::getInvalidTxList``); gaps
        # allowed only where a minSeqNum precondition admits them —
        # is_bad_seq decides, not a set-level rule. The chain must be
        # checked in APPLY order: sorted queues for sequential phases,
        # declared cluster order for a parallel soroban phase (clusters
        # are dependency chains — a descending-seq cluster must fail
        # here, not at apply).
        if self.parallel_stages is not None:
            classic = [f for f in self.frames
                       if id(f) not in self._soroban_ids]
            queues = _build_account_queues(classic)
            for stage in self.parallel_stages:
                for cluster in stage:
                    for f in cluster:
                        queues.setdefault(
                            f.source_account_id().value, []).append(f)
        else:
            queues = _build_account_queues(self.frames)
        for q in queues.values():
            current = 0
            for f in q:
                res = f.check_valid(ltx, current, lower_offset,
                                    upper_offset)
                if res.code not in (TC.txSUCCESS,
                                    TC.txFEE_BUMP_INNER_SUCCESS):
                    return False
                current = f.seq_num
        return True

    # ---------------- apply order ----------------

    def get_txs_in_apply_order(self) -> List:
        """Reference ``sortedForApplySequential`` applied per phase:
        classic applies first, then the soroban phase. A parallel
        soroban phase applies stage by stage, clusters in declared
        order (each cluster is a dependency chain)."""
        classic = [f for f in self.frames
                   if id(f) not in self._soroban_ids]
        out = self._phase_apply_order(classic)
        if self.parallel_stages is not None:
            for stage in self.parallel_stages:
                for cluster in stage:
                    out.extend(cluster)
            return out
        soroban = [f for f in self.frames if id(f) in self._soroban_ids]
        return out + self._phase_apply_order(soroban)

    def _phase_apply_order(self, frames) -> List:
        """Round-robin account batches, each shuffled by full-hash XOR
        set-hash."""
        queues = list(_build_account_queues(frames).values())
        batches: List[List] = []
        while queues:
            batch = []
            nxt = []
            for q in queues:
                batch.append(q.pop(0))
                if q:
                    nxt.append(q)
            queues = nxt
            batches.append(batch)
        out: List = []
        for batch in batches:
            batch.sort(key=lambda f: _xored(full_tx_hash(f), self.hash))
            out.extend(batch)
        return out

    def summary(self) -> str:
        return (f"txset(txs={self.size_tx()}, ops={self.size_op()}, "
                f"hash={self.hash.hex()[:8]})")


def _declared_access(f):
    res = (f.inner if hasattr(f, "inner") else f) \
        .tx.ext.value.resources
    return (len(res.footprint.readOnly) + len(res.footprint.readWrite),
            res.readBytes,
            len(res.footprint.readWrite),
            res.writeBytes)


def _soroban_ledger_caps_exceeded(frames, cfg) -> bool:
    """Do the set's declared aggregates exceed the per-ledger caps?"""
    caps = (cfg.ledger_max_read_ledger_entries,
            cfg.ledger_max_read_bytes,
            cfg.ledger_max_write_ledger_entries,
            cfg.ledger_max_write_bytes)
    totals = [0, 0, 0, 0]
    for f in frames:
        for i, d in enumerate(_declared_access(f)):
            totals[i] += d
    return any(t > c for t, c in zip(totals, caps))


def _enforce_soroban_ledger_caps(frames, cfg):
    """Greedy per-LEDGER aggregate access caps over the soroban phase
    (reference ledgerMaxRead*/ledgerMaxWrite* set-building limits):
    walk the already-priority-ordered selection and drop anything that
    would push a declared aggregate over its cap."""
    caps = (cfg.ledger_max_read_ledger_entries,
            cfg.ledger_max_read_bytes,
            cfg.ledger_max_write_ledger_entries,
            cfg.ledger_max_write_bytes)
    used = [0, 0, 0, 0]
    kept, dropped = [], []
    for f in frames:
        decl = _declared_access(f)
        if all(u + d <= c for u, d, c in zip(used, decl, caps)):
            for i, d in enumerate(decl):
                used[i] += d
            kept.append(f)
        else:
            dropped.append(f)
    return kept, dropped


def prefetch_signature_batch(ltx, frames) -> list:
    """Collect every plausible (pubkey, payload, signature) triple in the
    set and verify them in one device batch, seeding the verify cache.
    Returns the collected triples so callers can re-seed later without
    re-collecting."""
    items = collect_signature_triples(ltx, frames)
    batch_verify_into_cache(items)
    return items


def collect_signature_triples(ltx, frames) -> list:
    """Every plausible (pubkey, payload, signature) triple in the set.

    Candidates per tx: master key + account signers of the tx source,
    every op source, the fee source (fee bumps), and extraSigners —
    filtered by the 4-byte hint.
    """
    items = []
    # one account load per DISTINCT account for the whole set — the
    # collection must stay O(accounts) loads, not O(sigs x accounts)
    # (each load copies the entry)
    acct_cache: dict = {}

    def acct_for(account_id_v):
        k = account_id_v.value  # ed25519 bytes identify the account
        if k not in acct_cache:
            entry = ltx.load_without_record(account_key(account_id_v))
            acct_cache[k] = None if entry is None else entry.data.value
        return acct_cache[k]

    for f in frames:
        inner_frames = [f]
        if hasattr(f, "inner"):  # fee bump: outer + inner
            for sig in f.signatures:
                _collect_for_account(
                    acct_for(f.fee_source_id()), f.contents_hash(),
                    sig, items)
            inner_frames = [f.inner]
        for tf in inner_frames:
            h = tf.contents_hash()
            account_ids = [tf.source_account_id()]
            for op in tf.op_frames:
                aid = op.source_account_id()
                if aid not in account_ids:
                    account_ids.append(aid)
            accts = [acct_for(aid) for aid in account_ids]
            for sig in tf.signatures:
                for acc in accts:
                    _collect_for_account(acc, h, sig, items)
                for sk in tf.extra_signers():
                    _collect_for_signer_key(sk, h, sig, items)
    return items


def _collect_for_account(acc, h: bytes, sig, items):
    from stellar_tpu.tx.signature_utils import does_hint_match
    if acc is None:
        return
    pk = acc.accountID.value
    if does_hint_match(pk, sig.hint):
        items.append((pk, h, sig.signature))
    for s in acc.signers:
        _collect_for_signer_key(s.key, h, sig, items)


def _collect_for_signer_key(key, h: bytes, sig, items):
    from stellar_tpu.tx.signature_utils import (
        does_hint_match, signed_payload_hint,
    )
    if key.arm == SignerKeyType.SIGNER_KEY_TYPE_ED25519:
        if does_hint_match(key.value, sig.hint):
            items.append((key.value, h, sig.signature))
    elif key.arm == SignerKeyType.SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD:
        if sig.hint == signed_payload_hint(key.value):
            items.append((key.value.ed25519, key.value.payload,
                          sig.signature))
