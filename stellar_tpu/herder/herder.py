"""Herder: the concrete SCP driver — slot = ledger sequence, value =
``StellarValue{txSetHash, closeTime, upgrades}`` — plus the glue between
the tx queue, tx sets, SCP, and the ledger close (reference
``src/herder/HerderImpl.cpp`` / ``HerderSCPDriver.cpp``).

Pipeline per ledger (reference call stack §3.3 of SURVEY.md):

  trigger_next_ledger: queue -> makeTxSetFromTransactions -> nominate
  recv_scp_envelope:   verify sig -> (txset known?) -> SCP
  value_externalized:  StellarValue -> LedgerCloseData -> closeLedger
                       -> queue shift/ban -> re-trigger after the
                       remainder of EXP_LEDGER_TIMESPAN

Envelope signatures are over (networkID ‖ ENVELOPE_TYPE_SCP ‖ statement)
— sig hot path #2 (``HerderImpl.cpp:2413-2431``); bulk floods should go
through ``prefetch_envelope_signatures`` to ride the TPU batch verifier.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from stellar_tpu.crypto.keys import (
    SecretKey, batch_verify_into_cache, cached_verify_sig,
    verify_sig,
)
from stellar_tpu.crypto.tenant import peer_tenant
from stellar_tpu.crypto.verify_service import service_verified
from stellar_tpu.herder.transaction_queue import AddResult, TransactionQueue
from stellar_tpu.herder.tx_set import (
    ApplicableTxSetFrame, TxSetXDRFrame, make_tx_set_from_transactions,
)
from stellar_tpu.ledger.ledger_manager import LedgerCloseData, LedgerManager
from stellar_tpu.ledger.ledger_txn import LedgerTxn
from stellar_tpu.scp import SCP, EnvelopeState, SCPDriver, ValidationLevel
from stellar_tpu.scp.slot import BALLOT_PROTOCOL_TIMER, NOMINATION_TIMER
from stellar_tpu.utils.timer import VirtualClock, VirtualTimer
from stellar_tpu.xdr.ledger import StellarValue, basic_stellar_value
from stellar_tpu.xdr.runtime import Packer, from_bytes, to_bytes
from stellar_tpu.xdr.scp import SCPEnvelope, SCPQuorumSet, SCPStatement
from stellar_tpu.xdr.types import EnvelopeType

__all__ = ["Herder", "HERDER_STATE"]

# reference src/herder/Herder.cpp:7-22
EXP_LEDGER_TIMESPAN_SECONDS = 5
MAX_SCP_TIMEOUT_SECONDS = 240
CONSENSUS_STUCK_TIMEOUT_SECONDS = 35
MAX_TIME_SLIP_SECONDS = 60
LEDGER_VALIDITY_BRACKET = 100  # max slots ahead we accept
SCP_EXTRA_LOOKBACK_LEDGERS = 3


class HERDER_STATE:
    BOOTING = 0
    TRACKING = 1
    OUT_OF_SYNC = 2


def scp_envelope_sign_payload(network_id: bytes,
                              statement: SCPStatement) -> bytes:
    """(networkID ‖ ENVELOPE_TYPE_SCP ‖ statement) — what validators
    sign (reference ``HerderImpl::signEnvelope``)."""
    p = Packer()
    p.pack_fopaque(32, network_id)
    p.pack_int(EnvelopeType.ENVELOPE_TYPE_SCP)
    SCPStatement.pack(p, statement)
    return p.bytes()


class _HerderSCPDriver(SCPDriver):
    """SCP callbacks bound to a Herder (reference HerderSCPDriver)."""

    def __init__(self, herder: "Herder"):
        self.herder = herder

    # -- values --

    def get_node_weight(self, node_id, qset, is_local: bool) -> int:
        """Application-specific nomination weights from protocol 22
        (reference ``HerderSCPDriver::getNodeWeight``,
        HerderSCPDriver.cpp:1287-1352): a validator's weight is its
        quality level's weight divided by its home-domain size; falls
        back to the qset-structural weight below p22, under
        FORCE_OLD_STYLE_LEADER_ELECTION, with a manual QUORUM_SET, or
        for nodes outside the declared validator list."""
        h = self.herder
        cfg = h.node_config
        if cfg is None or \
                getattr(cfg, "FORCE_OLD_STYLE_LEADER_ELECTION", False) \
                or h.lm.last_closed_header.ledgerVersion < 22:
            return super().get_node_weight(node_id, qset, is_local)
        vwc = cfg.validator_weight_config() \
            if hasattr(cfg, "validator_weight_config") else None
        if vwc is None:
            return super().get_node_weight(node_id, qset, is_local)
        from stellar_tpu.scp.quorum import node_key
        entry = vwc["entries"].get(node_key(node_id))
        if entry is None:
            return super().get_node_weight(node_id, qset, is_local)
        domain, quality = entry
        return vwc["quality_weights"][quality] // \
            vwc["domain_sizes"][domain]

    def validate_value(self, slot_index, value, nomination):
        return self.herder._validate_value(slot_index, value, nomination)

    def extract_valid_value(self, slot_index, value):
        return self.herder._extract_valid_value(slot_index, value)

    def combine_candidates(self, slot_index, candidates):
        return self.herder._combine_candidates(slot_index, candidates)

    # -- plumbing --

    def sign_envelope(self, statement):
        sig = self.herder.secret_key.sign(
            scp_envelope_sign_payload(self.herder.network_id, statement))
        return SCPEnvelope(statement=statement, signature=sig)

    def emit_envelope(self, envelope):
        self.herder._emit_envelope(envelope)

    def get_qset(self, qset_hash):
        return self.herder.qsets.get(qset_hash)

    def setup_timer(self, slot_index, timer_id, timeout_ms, callback):
        self.herder._setup_timer(slot_index, timer_id, timeout_ms,
                                 callback)

    def compute_timeout(self, round_number):
        secs = min(round_number, MAX_SCP_TIMEOUT_SECONDS)
        return secs * 1000

    # -- notifications --

    def value_externalized(self, slot_index, value):
        self.herder._value_externalized(slot_index, value)


def _excluded_op_types(names) -> frozenset:
    """OperationType values for configured names (reference
    EXCLUDE_TRANSACTIONS_CONTAINING_OPERATION_TYPE); unknown names are
    a config error."""
    if not names:
        return frozenset()
    from stellar_tpu.xdr.tx import OperationType
    out = set()
    for name in names:
        t = getattr(OperationType, name, None)
        if t is None:
            raise ValueError(f"unknown operation type {name!r} in "
                             "EXCLUDE_TRANSACTIONS_CONTAINING_"
                             "OPERATION_TYPE")
        out.add(t)
    return frozenset(out)


class Herder:
    def __init__(self, secret_key: SecretKey, network_id: bytes,
                 ledger_manager: LedgerManager, clock: VirtualClock,
                 qset: SCPQuorumSet, is_validator: bool = True,
                 target_close_seconds: int = EXP_LEDGER_TIMESPAN_SECONDS,
                 max_slots_to_remember: int = 12,
                 node_config=None):
        self.secret_key = secret_key
        self.network_id = network_id
        self.lm = ledger_manager
        self.clock = clock
        self.target_close_seconds = target_close_seconds
        # operational knobs (reference Config.h); node_config is the
        # main Config when running inside an Application
        self.node_config = node_config
        # externalized-slot retention (reference MAX_SLOTS_TO_REMEMBER)
        self.max_slots_to_remember = max(max_slots_to_remember,
                                         SCP_EXTRA_LOOKBACK_LEDGERS)
        self.driver = _HerderSCPDriver(self)
        self.scp = SCP(self.driver, secret_key.public_key.raw,
                       is_validator, qset)
        from stellar_tpu.xdr.scp import quorum_set_hash
        self.qsets: Dict[bytes, SCPQuorumSet] = {
            quorum_set_hash(qset): qset}
        from stellar_tpu.herder.upgrades import Upgrades
        self.upgrades = Upgrades()
        # txset hash -> ApplicableTxSetFrame (PendingEnvelopes role)
        self.tx_sets: Dict[bytes, ApplicableTxSetFrame] = {}
        # envelopes waiting for their txset: txset hash -> [envelope]
        self.waiting_envelopes: Dict[bytes, List[SCPEnvelope]] = {}
        # envelopes waiting for an unknown quorum set
        self.waiting_for_qset: Dict[bytes, List[SCPEnvelope]] = {}
        # background quorum-intersection analysis state (reference
        # mLastQuorumMapIntersectionState)
        self._qic_last_hash: bytes = b""
        self._qic_inflight = None
        self.latest_quorum_intersection: Optional[dict] = None
        # fetch hooks (wired by the overlay): ask peers for missing items
        self.request_tx_set: Callable = lambda h: None
        self.request_quorum_set: Callable = lambda h: None
        # queue capacities scale the ledger limits by the configured
        # multipliers (reference TRANSACTION_QUEUE_SIZE_MULTIPLIER /
        # SOROBAN_TRANSACTION_QUEUE_SIZE_MULTIPLIER); excluded op types
        # and the ban depth ride the same Config
        _mult = getattr(node_config,
                        "TRANSACTION_QUEUE_SIZE_MULTIPLIER", 2)
        _smult = getattr(node_config,
                         "SOROBAN_TRANSACTION_QUEUE_SIZE_MULTIPLIER", 2)
        _ban = getattr(node_config, "TRANSACTION_QUEUE_BAN_LEDGERS", 10)
        _excluded = _excluded_op_types(getattr(
            node_config,
            "EXCLUDE_TRANSACTIONS_CONTAINING_OPERATION_TYPE", ()))
        self.tx_queue = TransactionQueue(
            max_ops=_mult * self.lm.last_closed_header.maxTxSetSize,
            check_valid=self._check_tx_valid, ban_ledgers=_ban,
            excluded_op_types=_excluded)
        # Soroban txs queue separately with their own (tx-count) limits
        # (reference SorobanTransactionQueue); pull-mode relay and set
        # building see both through the facade methods below
        _scfg = getattr(ledger_manager, "soroban_config", None)
        if _scfg is None:
            from stellar_tpu.tx.ops.soroban_ops import (
                default_soroban_config,
            )
            _scfg = default_soroban_config()
        self.soroban_tx_queue = TransactionQueue(
            max_ops=_smult * _scfg.ledger_max_tx_count,
            check_valid=self._check_tx_valid, ban_ledgers=_ban,
            excluded_op_types=_excluded)
        self.state = HERDER_STATE.BOOTING
        self.tracking_slot = 0
        # buffering + catchup arbitration for out-of-order externalizes
        # (reference LedgerApplyManagerImpl::processLedger); applies go
        # through _apply_externalized so drains carry full bookkeeping
        from stellar_tpu.catchup.catchup import LedgerApplyManager
        self.ledger_apply = LedgerApplyManager(
            ledger_manager, apply_fn=self._apply_externalized)
        self.on_catchup_needed = None  # app hook: start archive catchup
        self._timers: Dict[tuple, VirtualTimer] = {}
        self._trigger_timer = VirtualTimer(clock)
        self._stuck_timer = VirtualTimer(clock)
        self.request_scp_state = None  # overlay hook: pull peers' state
        # overlay hook: settle off-crank preverification before a
        # proposal is built (deterministic resolve point)
        self.before_nomination: Optional[Callable] = None
        self._trigger_armed_for = 0
        self._last_trigger_at = 0.0
        # network hooks (set by overlay / simulation): fan out to peers
        self.broadcast_envelope: Callable = lambda env: None
        self.broadcast_tx_set: Callable = lambda frame: None
        self.broadcast_transaction: Callable = lambda frame: None
        # herder-level observers
        self.on_externalized: Optional[Callable] = None

    # ---------------- qset/txset registry ----------------

    def register_qset(self, qset: SCPQuorumSet):
        from stellar_tpu.xdr.scp import quorum_set_hash
        h = quorum_set_hash(qset)
        if h in self.qsets:
            return
        self.qsets[h] = qset
        for env in self.waiting_for_qset.pop(h, []):
            self.recv_scp_envelope(env)

    def recv_tx_set(self, frame) -> bool:
        """Register a tx set heard from the network; releases any SCP
        envelopes waiting on it (reference
        ``PendingEnvelopes::recvTxSet``)."""
        if isinstance(frame, TxSetXDRFrame):
            applicable = frame.prepare_for_apply(self.network_id)
            if applicable is None:
                return False
        else:
            applicable = frame
        h = applicable.hash
        if h in self.tx_sets:
            return True
        self.tx_sets[h] = applicable
        # release held envelopes — but only those with no OTHER missing
        # tx set (an envelope held under several hashes is fed exactly
        # once, when its last dependency arrives)
        for env in self.waiting_envelopes.pop(h, []):
            if not self._missing_tx_sets(env.statement):
                self._feed_scp(env)
        return True

    def get_tx_set(self, h: bytes) -> Optional[ApplicableTxSetFrame]:
        return self.tx_sets.get(h)

    # ---------------- transactions ----------------

    def _check_tx_valid(self, frame, current_seq: int = 0):
        with LedgerTxn(self.lm.root) as ltx:
            res = frame.check_valid(
                ltx, current_seq, 0, self.target_close_seconds)
            ltx.rollback()
        return res

    def recv_transaction(self, frame, submitted_from_self=False
                         ) -> AddResult:
        """Reference ``HerderImpl::recvTransaction``: admit to the queue
        and flood on success."""
        res = self.queue_for(frame).try_add(frame)
        if res.code == AddResult.ADD_STATUS_PENDING:
            self.broadcast_transaction(frame)
        return res

    def queue_for(self, frame) -> TransactionQueue:
        return self.soroban_tx_queue if frame.is_soroban() \
            else self.tx_queue

    def get_pending_tx(self, tx_hash: bytes):
        """Pull-mode demand lookup across both queues."""
        return self.tx_queue.known_hashes.get(tx_hash) or \
            self.soroban_tx_queue.known_hashes.get(tx_hash)

    def is_tx_known_or_banned(self, tx_hash: bytes) -> bool:
        return (tx_hash in self.tx_queue.known_hashes or
                tx_hash in self.soroban_tx_queue.known_hashes or
                self.tx_queue.is_banned(tx_hash) or
                self.soroban_tx_queue.is_banned(tx_hash))

    # ---------------- SCP envelopes ----------------

    def verify_envelope(self, env: SCPEnvelope) -> bool:
        """Sig hot path #2 (reference ``HerderImpl::verifyEnvelope``).

        When the resident verify service is running
        (``VERIFY_SERVICE_ENABLED``), the envelope rides the ``scp``
        priority lane — the one lane the shed ladder NEVER sheds, so
        consensus keeps making progress while bulk work sheds under
        overload. A ``batch_verify_into_cache`` prefetch still wins
        (cache consulted first), the verdict re-seeds that cache so
        flood dedup stays O(1), and ingress rejection or any
        service-side failure falls back to the direct path — the
        decision is bit-identical on every route, so the service can
        only ever change latency, never validity."""
        payload = scp_envelope_sign_payload(self.network_id,
                                            env.statement)
        pk = env.statement.nodeID.value
        got = cached_verify_sig(pk, payload, env.signature)
        if got is not None:
            return got
        # shared adopter block (service_verified): bounded wait +
        # cache seeding + any-failure fallback — previously this call
        # had NO result timeout, so a wedged dispatcher could park
        # the consensus crank on an unresolved scp ticket. The round
        # trip is tenant-tagged with the envelope's VALIDATOR identity
        # when VERIFY_TENANT_FROM_PEER is on (ISSUE 15 follow-on to
        # the ISSUE 14 quotas), so one flooding validator degrades
        # itself, not the whole scp lane; off (the default) keeps the
        # quota-exempt un-tenanted stream byte-identical.
        res = service_verified([(pk, payload, env.signature)],
                               lane="scp", tenant=peer_tenant(pk))
        if res is not None:
            return res[0]
        return verify_sig(pk, payload, env.signature)

    def prefetch_envelope_signatures(self, envs: List[SCPEnvelope]):
        """Batch-verify an envelope flood in one device round trip; the
        per-envelope verify_envelope calls then hit the cache."""
        batch_verify_into_cache([
            (e.statement.nodeID.value,
             scp_envelope_sign_payload(self.network_id, e.statement),
             e.signature)
            for e in envs])

    def recv_scp_envelope(self, env: SCPEnvelope) -> int:
        """Reference ``HerderImpl::recvSCPEnvelope``."""
        from stellar_tpu.utils.tracing import zone
        with zone("herder.recvSCPEnvelope"):
            return self._recv_scp_envelope_inner(env)

    def _recv_scp_envelope_inner(self, env: SCPEnvelope) -> int:
        if not self.verify_envelope(env):
            return EnvelopeState.INVALID
        slot = env.statement.slotIndex
        low = max(1, self.lm.ledger_seq - SCP_EXTRA_LOOKBACK_LEDGERS)
        if slot < low or \
                slot > self.lm.ledger_seq + LEDGER_VALIDITY_BRACKET:
            return EnvelopeState.INVALID
        # hold envelopes pledging under a quorum set we don't know yet
        # (reference PendingEnvelopes qset fetch)
        qh = self._statement_qset_hash(env.statement)
        if qh not in self.qsets:
            self.waiting_for_qset.setdefault(qh, []).append(env)
            self.request_quorum_set(qh)
            return EnvelopeState.VALID
        # hold envelopes whose tx sets we don't have yet
        missing = self._missing_tx_sets(env.statement)
        if missing:
            for h in missing:
                self.waiting_envelopes.setdefault(h, []).append(env)
                self.request_tx_set(h)
            return EnvelopeState.VALID
        return self._feed_scp(env)

    @staticmethod
    def _statement_qset_hash(st: SCPStatement) -> bytes:
        from stellar_tpu.xdr.scp import SCPStatementType as T
        p = st.pledges.value
        if st.pledges.arm == T.SCP_ST_EXTERNALIZE:
            return p.commitQuorumSetHash
        return p.quorumSetHash

    def _feed_scp(self, env: SCPEnvelope) -> int:
        return self.scp.receive_envelope(env)

    def _missing_tx_sets(self, st: SCPStatement) -> List[bytes]:
        out = []
        for v in self._statement_values(st):
            sv = _parse_stellar_value(v)
            if sv is not None and sv.txSetHash not in self.tx_sets \
                    and sv.txSetHash not in out:
                out.append(sv.txSetHash)
        return out

    @staticmethod
    def _statement_values(st: SCPStatement) -> List[bytes]:
        from stellar_tpu.xdr.scp import SCPStatementType as T
        t = st.pledges.arm
        p = st.pledges.value
        if t == T.SCP_ST_NOMINATE:
            return list(p.votes) + list(p.accepted)
        if t == T.SCP_ST_PREPARE:
            vals = [p.ballot.value]
            if p.prepared is not None:
                vals.append(p.prepared.value)
            if p.preparedPrime is not None:
                vals.append(p.preparedPrime.value)
            return vals
        if t == T.SCP_ST_CONFIRM:
            return [p.ballot.value]
        return [p.commit.value]

    # ---------------- value validation / combination ----------------

    def _closetime_drift(self) -> int:
        """Configured MAXIMUM_LEDGER_CLOSETIME_DRIFT, or the
        reference's derivation: min((slots+2) * close cadence, 90s)
        (Config.cpp:196-204)."""
        cfg = getattr(self.node_config,
                      "MAXIMUM_LEDGER_CLOSETIME_DRIFT", 0)
        if cfg > 0:
            return cfg
        return min((self.max_slots_to_remember + 2) *
                   self.target_close_seconds, 90)

    def _validate_value(self, slot_index: int, value: bytes,
                        nomination: bool) -> int:
        sv = _parse_stellar_value(value)
        if sv is None:
            return ValidationLevel.INVALID
        lcl = self.lm.last_closed_header
        # close time advances strictly, and not absurdly into the future
        if sv.closeTime <= lcl.scpValue.closeTime:
            return ValidationLevel.INVALID
        if nomination:
            now = self.clock.system_now()
            if sv.closeTime > now + MAX_TIME_SLIP_SECONDS:
                return ValidationLevel.INVALID
            # and not absurdly in the past either (reference
            # MAXIMUM_LEDGER_CLOSETIME_DRIFT, HerderImpl.cpp:656-658;
            # 0 derives the reference's MAX_SLOTS_TO_REMEMBER bound)
            drift = self._closetime_drift()
            if now >= drift and sv.closeTime < now - drift:
                return ValidationLevel.INVALID
        # every carried upgrade must be apply-valid (and, at nomination,
        # exactly what this node scheduled) — reference
        # validateUpgrades in HerderSCPDriver::validateValueHelper
        for raw in sv.upgrades:
            if not self.upgrades.is_valid(
                    raw, lcl, nomination, sv.closeTime,
                    state_getter=self.lm.root.store.get):
                return ValidationLevel.INVALID
        if slot_index != lcl.ledgerSeq + 1:
            # can't fully validate against a non-current ledger
            return ValidationLevel.MAYBE_VALID
        txset = self.tx_sets.get(sv.txSetHash)
        if txset is None:
            return ValidationLevel.MAYBE_VALID
        with LedgerTxn(self.lm.root) as ltx:
            ok = txset.check_valid(ltx, self.lm.last_closed_hash)
            ltx.rollback()
        return ValidationLevel.FULLY_VALIDATED if ok \
            else ValidationLevel.INVALID

    def _extract_valid_value(self, slot_index: int, value: bytes
                             ) -> Optional[bytes]:
        """Salvage a nominated value by stripping upgrades this node
        won't vote for (reference
        ``HerderSCPDriver::extractValidValue``)."""
        sv = _parse_stellar_value(value)
        if sv is None:
            return None
        lcl = self.lm.last_closed_header
        if sv.closeTime <= lcl.scpValue.closeTime:
            return None
        kept = [u for u in sv.upgrades
                if self.upgrades.is_valid(
                    u, lcl, True, sv.closeTime,
                    state_getter=self.lm.root.store.get)]
        if len(kept) == len(sv.upgrades):
            return value
        return to_bytes(StellarValue, basic_stellar_value(
            sv.txSetHash, sv.closeTime, upgrades=kept))

    def _combine_candidates(self, slot_index: int,
                            candidates) -> Optional[bytes]:
        """Pick the best txset (most ops, xored-hash tiebreak), max
        closeTime, merged upgrades (reference
        ``HerderSCPDriver::combineCandidates``)."""
        from stellar_tpu.crypto.sha import sha256
        parsed = []
        for v in sorted(candidates):
            sv = _parse_stellar_value(v)
            if sv is not None:
                parsed.append(sv)
        if not parsed:
            return None
        candidates_hash = sha256(b"".join(sorted(candidates)))
        best = None
        best_key = None
        max_close = 0
        upgrades: Dict[int, object] = {}
        for sv in parsed:
            max_close = max(max_close, sv.closeTime)
            txset = self.tx_sets.get(sv.txSetHash)
            ops = txset.size_op() if txset is not None else 0
            xored = bytes(a ^ b for a, b in
                          zip(sv.txSetHash, candidates_hash))
            key = (ops, xored)
            if best_key is None or key > best_key:
                best_key = key
                best = sv
            for raw in sv.upgrades:
                from stellar_tpu.xdr.ledger import LedgerUpgrade
                try:
                    up = from_bytes(LedgerUpgrade, bytes(raw))
                except Exception:
                    continue
                cur = upgrades.get(up.arm)
                if cur is None or up.value > cur.value:
                    upgrades[up.arm] = up
        from stellar_tpu.xdr.ledger import LedgerUpgrade
        up_bytes = [to_bytes(LedgerUpgrade, upgrades[t])
                    for t in sorted(upgrades)]
        out = basic_stellar_value(best.txSetHash, max_close, up_bytes)
        return to_bytes(StellarValue, out)

    # ---------------- timers ----------------

    def _setup_timer(self, slot_index, timer_id, timeout_ms, callback):
        key = (slot_index, timer_id)
        timer = self._timers.get(key)
        if timer is None:
            timer = self._timers[key] = VirtualTimer(self.clock)
        timer.cancel()
        if callback is not None and timeout_ms >= 0:
            timer.expires_from_now(timeout_ms / 1000.0)
            timer.async_wait(callback)

    # ---------------- nomination trigger ----------------

    def start(self):
        """Begin participating: arm the first ledger trigger."""
        self.state = HERDER_STATE.TRACKING
        self.tracking_slot = self.lm.ledger_seq + 1
        self._arm_trigger(0.0)
        self._arm_stuck_timer()

    # ---------------- stuck detection / out-of-sync recovery --------

    def _arm_stuck_timer(self):
        """Reference ``Herder::CONSENSUS_STUCK_TIMEOUT_SECONDS``: no
        externalize for 35s -> lost sync."""
        self._stuck_timer.cancel()
        self._stuck_timer.expires_from_now(
            CONSENSUS_STUCK_TIMEOUT_SECONDS)
        self._stuck_timer.async_wait(self._lost_sync)

    def _lost_sync(self):
        """Reference ``HerderImpl::lostSync`` + out-of-sync recovery:
        flag the state and periodically pull peers' SCP state until an
        externalize restores tracking."""
        self.state = HERDER_STATE.OUT_OF_SYNC
        from stellar_tpu.utils.metrics import registry
        registry.counter("herder.lost-sync").inc()
        self._out_of_sync_recovery()

    def _out_of_sync_recovery(self):
        if self.state != HERDER_STATE.OUT_OF_SYNC:
            return
        if self.request_scp_state is not None:
            self.request_scp_state(self.lm.ledger_seq + 1)
        # keep nudging at close cadence until tracking returns
        self._stuck_timer.cancel()
        self._stuck_timer.expires_from_now(self.target_close_seconds)
        self._stuck_timer.async_wait(self._out_of_sync_recovery)

    def _arm_trigger(self, delay: float):
        seq = self.lm.ledger_seq + 1
        self._trigger_armed_for = seq
        self._trigger_timer.cancel()
        self._trigger_timer.expires_from_now(max(0.0, delay))
        self._trigger_timer.async_wait(
            lambda: self.trigger_next_ledger(seq))

    def trigger_next_ledger(self, ledger_seq_to_trigger: int):
        """Reference ``HerderImpl::triggerNextLedger``: build + nominate
        this node's proposal."""
        if ledger_seq_to_trigger != self.lm.ledger_seq + 1:
            return
        # deterministic resolve point: any off-crank pre-verified tx
        # floods must land in the queues BEFORE the proposal is built
        # (virtual-clock cranks would otherwise race real worker
        # threads — the single-writer-crank determinism rule)
        if self.before_nomination is not None:
            self.before_nomination()
        self._last_trigger_at = self.clock.now()
        lcl = self.lm.last_closed_header
        frames = self.tx_queue.get_transactions() + \
            self.soroban_tx_queue.get_transactions()
        txset, _ = make_tx_set_from_transactions(
            frames, lcl, self.lm.last_closed_hash,
            soroban_config=getattr(self.lm, "soroban_config", None),
            max_dex_ops=getattr(self.node_config,
                                "MAX_DEX_TX_OPERATIONS_IN_TX_SET",
                                None))
        self.recv_tx_set(txset)
        self.broadcast_tx_set(txset)
        close_time = max(self.clock.system_now(),
                         lcl.scpValue.closeTime + 1)
        sv = basic_stellar_value(
            txset.hash, close_time,
            upgrades=self.upgrades.create_upgrades_for(
                lcl, close_time,
                soroban_config=getattr(self.lm, "soroban_config", None),
                state_getter=self.lm.root.store.get
                if hasattr(self.lm.root, "store") else None))
        prev = to_bytes(StellarValue, lcl.scpValue)
        self.scp.nominate(ledger_seq_to_trigger,
                          to_bytes(StellarValue, sv), prev)

    # ---------------- externalize ----------------

    def _emit_envelope(self, envelope: SCPEnvelope):
        self.broadcast_envelope(envelope)

    def _value_externalized(self, slot_index: int, value: bytes):
        """Reference ``HerderImpl::valueExternalized`` →
        ``LedgerManager::valueExternalized`` →
        ``LedgerApplyManager::processLedger``: apply in sequence,
        buffer ahead-of-LCL slots, signal catchup when the gap grows."""
        sv = _parse_stellar_value(value)
        if sv is None:
            raise RuntimeError("externalized unparsable value")
        txset = self.tx_sets.get(sv.txSetHash)
        if txset is None:
            raise RuntimeError("externalized unknown tx set")
        if slot_index <= self.lm.ledger_seq:
            return  # stale: already applied
        lcd = LedgerCloseData(
            ledger_seq=slot_index, tx_set=txset,
            close_time=sv.closeTime, upgrades=list(sv.upgrades))
        outcome = self.ledger_apply.process_ledger(lcd)
        if outcome == "applied":
            return  # bookkeeping ran per applied close
        # ahead of the LCL: buffered; once the gap passes the trigger
        # depth, ask the application to catch up from archives
        # (reference LM_CATCHING_UP_STATE)
        self.state = HERDER_STATE.OUT_OF_SYNC
        if outcome == "catchup-needed" and \
                self.on_catchup_needed is not None:
            self.on_catchup_needed(slot_index)

    def drain_buffered(self):
        """Apply any buffered contiguous successors of the LCL (called
        after a catchup closes the gap)."""
        self.ledger_apply.drain()

    def _apply_externalized(self, lcd: LedgerCloseData):
        slot_index = lcd.ledger_seq
        txset = lcd.tx_set
        result = self.lm.close_ledger(lcd)
        self.upgrades.remove_upgrades_once_done(
            result.header,
            soroban_config=getattr(self.lm, "soroban_config", None),
            state_getter=self.lm.root.store.get
            if hasattr(self.lm.root, "store") else None)
        self.state = HERDER_STATE.TRACKING
        self.tracking_slot = slot_index + 1
        self._arm_stuck_timer()  # progress: reset the 35s watchdog
        # queue bookkeeping
        self.tx_queue.remove_applied(txset.frames)
        self.tx_queue.shift()
        # ledger limits can change via upgrades mid-run; re-derive the
        # queue caps with the CONFIGURED multipliers
        _mult = getattr(self.node_config,
                        "TRANSACTION_QUEUE_SIZE_MULTIPLIER", 2)
        _smult = getattr(self.node_config,
                         "SOROBAN_TRANSACTION_QUEUE_SIZE_MULTIPLIER", 2)
        self.tx_queue.max_ops = \
            _mult * self.lm.last_closed_header.maxTxSetSize
        self.soroban_tx_queue.remove_applied(txset.frames)
        self.soroban_tx_queue.shift()
        scfg = getattr(self.lm, "soroban_config", None)
        if scfg is not None:
            self.soroban_tx_queue.max_ops = \
                _smult * scfg.ledger_max_tx_count
        # GC old slots + their timers + txsets
        keep_from = max(1, slot_index - self.max_slots_to_remember)
        self.scp.purge_slots(keep_from)
        for key in [k for k in self._timers if k[0] < keep_from]:
            self._timers.pop(key).cancel()
        self._gc_tx_sets()
        self._maybe_reanalyze_quorum_map()
        if self.on_externalized is not None:
            self.on_externalized(slot_index, result)
        # pace the next ledger to the target cadence
        elapsed = self.clock.now() - self._last_trigger_at
        self._arm_trigger(max(0.0, self.target_close_seconds - elapsed))

    def _maybe_reanalyze_quorum_map(self):
        """Reference ``checkAndMaybeReanalyzeQuorumMap``
        (HerderImpl.cpp:1852-1905): when QUORUM_INTERSECTION_CHECKER
        is on and the tracked quorum map changed since the last
        analysis, re-run the bounded intersection check off-crank and
        remember the result (``latest_quorum_intersection``; a
        detected split is logged as an error)."""
        if self.node_config is None or not getattr(
                self.node_config, "QUORUM_INTERSECTION_CHECKER", False):
            return
        from stellar_tpu.utils import workers
        if not workers.background_enabled():
            # the bounded search can still cost millions of sat calls;
            # the reference only ever runs it off-thread, so inline
            # (deterministic/pessimized) modes skip it rather than
            # stall externalize
            return
        from stellar_tpu.crypto.sha import sha256
        from stellar_tpu.herder.quorum_tracker import QuorumTracker
        from stellar_tpu.xdr.scp import quorum_set_hash
        # SNAPSHOT on the crank thread: the worker must never touch
        # live herder state (workers contract: pure fn over immutable
        # inputs); the hash covers the node->qset ASSIGNMENT, not just
        # the learned-qset set (reference hashes the tracked map)
        qmap = QuorumTracker(self).node_qset_map()
        qmap_hash = sha256(b"".join(
            n + (quorum_set_hash(q) if q is not None else b"\x00" * 32)
            for n, q in sorted(qmap.items())))
        if qmap_hash == self._qic_last_hash or \
                self._qic_inflight is not None:
            return
        self._qic_last_hash = qmap_hash

        def run():
            return QuorumTracker(self).analyze(qmap=qmap)

        fut = workers.run_async(run)
        self._qic_inflight = fut

        def done(f):
            self._qic_inflight = None
            try:
                out = f.result()
            except Exception as e:
                import logging
                logging.getLogger("stellar_tpu.herder").warning(
                    "quorum intersection analysis failed: %s", e)
                # retry on the next externalize
                self._qic_last_hash = b""
                return
            self.latest_quorum_intersection = out
            if out.get("intersection") is False:
                import logging
                logging.getLogger("stellar_tpu.herder").error(
                    "POSSIBLE QUORUM SPLIT detected: %s",
                    out.get("split"))
        fut.add_done_callback(done)

    def _gc_tx_sets(self):
        """Keep only tx sets referenced by live slots' values."""
        live: set = set()
        for idx in self.scp.known_slots:
            slot = self.scp.known_slots[idx]
            for env in slot.get_current_state():
                for v in self._statement_values(env.statement):
                    sv = _parse_stellar_value(v)
                    if sv is not None:
                        live.add(sv.txSetHash)
        self.tx_sets = {h: t for h, t in self.tx_sets.items()
                        if h in live}
        # waiting envelopes for closed slots will never be fed; drop them
        self.waiting_envelopes = {
            h: kept for h, envs in self.waiting_envelopes.items()
            if (kept := [e for e in envs
                         if e.statement.slotIndex > self.lm.ledger_seq])}


def _parse_stellar_value(raw: bytes) -> Optional[StellarValue]:
    try:
        return from_bytes(StellarValue, bytes(raw))
    except Exception:
        return None
