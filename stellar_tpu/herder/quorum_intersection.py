"""Quorum-intersection checker (reference
``src/herder/QuorumIntersectionCheckerImpl.cpp`` — the Lachowski
branch-and-bound over minimal quorums, with the same early exits).

Given every node's quorum set, decide whether ANY two quorums of the
network must intersect. The search enumerates *minimal* quorums inside
the scan SCC; for each one found it checks whether the complement still
contains a quorum — if so, the pair is a concrete safety
counterexample (two quorums that can externalize different values).

Bitsets are plain Python ints (arbitrary-width, C-speed bitops), the
idiomatic stand-in for the reference's BitSet.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["QuorumIntersectionChecker"]


class QuorumIntersectionChecker:
    def __init__(self, qmap: Dict[bytes, "SCPQuorumSet"]):
        """qmap: node id (raw 32B) -> SCPQuorumSet. Nodes with missing
        qsets are treated as their own singleton qset (reference treats
        missing as unknown and excludes; singleton is the conservative
        local stance for fixtures)."""
        self.nodes: List[bytes] = sorted(qmap)
        self.index = {n: i for i, n in enumerate(self.nodes)}
        self.qsets = [qmap[n] for n in self.nodes]
        self.n = len(self.nodes)
        # per-node dependency mask (validators reachable through the
        # qset tree) for SCC construction and the split heuristic
        self._deps = [self._qset_mask(qs) for qs in self.qsets]
        self.last_split: Optional[Tuple[List[bytes], List[bytes]]] = None
        self.quorum_found = False
        self._calls = 0
        self.max_calls: Optional[int] = None  # interrupt knob

    # ---------------- qset evaluation ----------------

    def _qset_mask(self, qs) -> int:
        m = 0
        for v in qs.validators:
            i = self.index.get(v.value)
            if i is not None:
                m |= 1 << i
        for inner in qs.innerSets:
            m |= self._qset_mask(inner)
        return m

    def _sat(self, qs, mask: int) -> bool:
        """Does `mask` satisfy the qset? (reference isSatisfiedBy)."""
        hits = 0
        for v in qs.validators:
            i = self.index.get(v.value)
            if i is not None and (mask >> i) & 1:
                hits += 1
        for inner in qs.innerSets:
            if self._sat(inner, mask):
                hits += 1
        return hits >= qs.threshold

    def contract_to_maximal_quorum(self, mask: int) -> int:
        """Strip unsatisfied nodes to a fixpoint; the result (possibly
        0) is the unique maximal quorum inside ``mask``."""
        while True:
            out = 0
            m = mask
            while m:
                i = (m & -m).bit_length() - 1
                m &= m - 1
                if self._sat(self.qsets[i], mask):
                    out |= 1 << i
            if out == mask:
                return out
            mask = out

    def is_minimal_quorum(self, q: int) -> bool:
        m = q
        while m:
            i = (m & -m).bit_length() - 1
            m &= m - 1
            if self.contract_to_maximal_quorum(q & ~(1 << i)):
                return False
        return True

    # ---------------- SCCs (Tarjan) ----------------

    def _sccs(self) -> List[int]:
        index_of = [-1] * self.n
        low = [0] * self.n
        on_stack = [False] * self.n
        stack: List[int] = []
        sccs: List[int] = []
        counter = [0]

        def strongconnect(v):
            # iterative Tarjan to dodge recursion limits
            work = [(v, 0)]
            while work:
                node, pi = work[-1]
                if pi == 0:
                    index_of[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack[node] = True
                advanced = False
                deps = self._deps[node] & ~(1 << node)
                ds = []
                m = deps
                while m:
                    w = (m & -m).bit_length() - 1
                    m &= m - 1
                    ds.append(w)
                for idx in range(pi, len(ds)):
                    w = ds[idx]
                    if index_of[w] == -1:
                        work[-1] = (node, idx + 1)
                        work.append((w, 0))
                        advanced = True
                        break
                    if on_stack[w]:
                        low[node] = min(low[node], index_of[w])
                if advanced:
                    continue
                if low[node] == index_of[node]:
                    scc = 0
                    while True:
                        w = stack.pop()
                        on_stack[w] = False
                        scc |= 1 << w
                        if w == node:
                            break
                    sccs.append(scc)
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])

        for v in range(self.n):
            if index_of[v] == -1:
                strongconnect(v)
        return sccs

    # ---------------- the search ----------------

    def _mask_to_nodes(self, mask: int) -> List[bytes]:
        out = []
        m = mask
        while m:
            i = (m & -m).bit_length() - 1
            m &= m - 1
            out.append(self.nodes[i])
        return out

    def _note_split(self, a: int, b: int):
        self.last_split = (self._mask_to_nodes(a), self._mask_to_nodes(b))

    def _pick_split_node(self, remaining: int) -> int:
        """Most-depended-on node in remaining (the reference's
        in-degree heuristic)."""
        best, best_deg = None, -1
        m = remaining
        while m:
            i = (m & -m).bit_length() - 1
            m &= m - 1
            deg = sum(1 for d in self._deps if (d >> i) & 1)
            if deg > best_deg:
                best, best_deg = i, deg
        return best

    def _any_min_quorum_has_disjoint(self, committed: int, remaining: int,
                                     scan_scc: int) -> bool:
        self._calls += 1
        if self.max_calls is not None and self._calls > self.max_calls:
            raise TimeoutError("quorum intersection scan interrupted")
        # early exit 1: committed beyond half the SCC — the other branch
        # will find the min-quorum inside the complement
        if bin(committed).count("1") > \
                bin(scan_scc).count("1") // 2 + 1:
            return False
        # early exit 3: committed contains a quorum — terminal either way
        committed_q = self.contract_to_maximal_quorum(committed)
        if committed_q:
            if self.is_minimal_quorum(committed_q):
                disj = self.contract_to_maximal_quorum(
                    scan_scc & ~committed_q)
                if disj:
                    self._note_split(committed_q, disj)
                    return True
            return False
        # early exit 2: the perimeter must still contain a quorum
        # extending committed
        perimeter = committed | remaining
        ext_q = self.contract_to_maximal_quorum(perimeter)
        if not ext_q or (committed & ~ext_q):
            return False
        if not remaining:
            return False
        split = self._pick_split_node(remaining)
        remaining &= ~(1 << split)
        if self._any_min_quorum_has_disjoint(committed, remaining,
                                             scan_scc):
            return True
        return self._any_min_quorum_has_disjoint(committed | (1 << split),
                                                 remaining, scan_scc)

    def network_enjoys_quorum_intersection(self) -> bool:
        """False iff two disjoint quorums exist (split recorded in
        ``last_split``) — reference
        ``networkEnjoysQuorumIntersection``."""
        self.last_split = None
        self._calls = 0
        quorum_sccs = []
        for scc in self._sccs():
            q = self.contract_to_maximal_quorum(scc)
            if q:
                quorum_sccs.append(q)
        if not quorum_sccs:
            self.quorum_found = False
            return True  # vacuous: no quorums at all (reference warns)
        self.quorum_found = True
        if len(quorum_sccs) > 1:
            self._note_split(quorum_sccs[0], quorum_sccs[1])
            return False
        scan = quorum_sccs[0]
        return not self._any_min_quorum_has_disjoint(0, scan, scan)
