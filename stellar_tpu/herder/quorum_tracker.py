"""Transitive-quorum tracking (reference ``src/herder/QuorumTracker``
+ the SCC / quorum-health analytics behind the ``quorum`` admin
endpoint): expand the local quorum set through every quorum set learned
from SCP traffic, then analyze the resulting known subnetwork —
node count, closure completeness, quorum intersection, and
single-node criticality."""

from __future__ import annotations

from typing import Dict, Optional, Set

from stellar_tpu.scp.quorum import for_all_nodes, make_node_id
from stellar_tpu.xdr.scp import SCPQuorumSet

__all__ = ["QuorumTracker"]

# criticality analysis is combinatorial; cap the subnetwork size AND
# the checker's branch-and-bound work so a hostile learned topology
# can't stall the main thread (the analysis runs on it)
MAX_NODES_FOR_ANALYSIS = 20
MAX_CHECKER_CALLS = 200_000


def _fickle_qset(group: Set[bytes],
                 qmap: Dict[bytes, SCPQuorumSet]) -> SCPQuorumSet:
    """The reference's 'fickle' reconfiguration
    (``getIntersectionCriticalGroups``): the group goes along with
    anyone — threshold 2 of {the whole group, any one node that
    depends on a group member}."""
    pointers = sorted(
        n for n, q in qmap.items()
        if n not in group and q is not None and
        (for_all_nodes(q) & group))
    return SCPQuorumSet(
        threshold=2,
        validators=[],
        innerSets=[
            SCPQuorumSet(threshold=len(group),
                         validators=[make_node_id(n)
                                     for n in sorted(group)],
                         innerSets=[]),
            SCPQuorumSet(threshold=1,
                         validators=[make_node_id(n) for n in pointers],
                         innerSets=[]),
        ])


class QuorumTracker:
    """Rebuilds the transitive closure of the local quorum from the
    herder's learned quorum sets (reference ``QuorumTracker::rebuild``
    driven by PendingEnvelopes' qset fetches)."""

    def __init__(self, herder):
        self.herder = herder

    def node_qset_map(self) -> Dict[bytes, Optional[SCPQuorumSet]]:
        """node id -> its quorum set (None when not yet learned),
        starting from the local node and expanding through every
        learned qset reachable from it."""
        h = self.herder
        learned: Dict[bytes, SCPQuorumSet] = {}
        # nodes pledge their qset hash inside SCP statements; map
        # node -> latest pledged hash from the retained slots
        pledged: Dict[bytes, bytes] = {}
        for idx in sorted(h.scp.known_slots):
            slot = h.scp.known_slots[idx]
            for st, _ in slot.statements_history:
                pledged[st.nodeID.value] = h._statement_qset_hash(st)
        for node, qh in pledged.items():
            if qh in h.qsets:
                learned[node] = h.qsets[qh]
        local_id = h.scp.local_node_id
        learned[local_id] = h.scp.local_qset

        out: Dict[bytes, Optional[SCPQuorumSet]] = {}
        frontier = [local_id]
        while frontier:
            node = frontier.pop()
            if node in out:
                continue
            qs = learned.get(node)
            out[node] = qs
            if qs is not None:
                for dep in for_all_nodes(qs):
                    if dep not in out:
                        frontier.append(dep)
        return out

    def analyze(self, qmap=None) -> dict:
        """The ``quorum`` endpoint's transitive section (reference
        ``HerderImpl::getJsonTransitiveQuorumInfo``). Node ids use the
        same 16-hex-char short form as the endpoint's validator list.
        ``intersection`` is None when the closure is incomplete, too
        large, or the bounded search ran out of budget; ``split`` gives
        a counterexample when intersection is False. Pass a
        pre-snapshotted ``qmap`` to analyze off the main thread (the
        live herder state must only be read on the crank)."""
        if qmap is None:
            qmap = self.node_qset_map()
        unknown = [n for n, q in qmap.items() if q is None]
        out = {
            "node_count": len(qmap),
            "unknown_count": len(unknown),
            "fully_known": not unknown,
        }
        known = {n: q for n, q in qmap.items() if q is not None}
        if unknown or len(known) > MAX_NODES_FOR_ANALYSIS or not known:
            out["intersection"] = None  # not decidable yet / too big
            return out
        from stellar_tpu.herder.quorum_intersection import (
            QuorumIntersectionChecker,
        )
        checker = QuorumIntersectionChecker(known)
        checker.max_calls = MAX_CHECKER_CALLS
        try:
            out["intersection"] = \
                checker.network_enjoys_quorum_intersection()
        except TimeoutError:
            out["intersection"] = None  # budget exhausted: undecided
            return out
        if out["intersection"]:
            out["critical_nodes"] = [
                n.hex()[:16] for n in known
                if self._is_critical(known, {n})]
        else:
            out["split"] = [[n.hex()[:16] for n in side]
                            for side in checker.last_split]
        return out

    @staticmethod
    def _is_critical(known: Dict[bytes, SCPQuorumSet],
                     group: Set[bytes]) -> bool:
        """True when reconfiguring ``group`` as fickle (it will join
        anyone's quorum) lets the network split — the reference's
        intersection-criticality test, here run per singleton node
        (the reference also examines leaf inner-set groups). Undecided
        within the work budget counts as not-critical."""
        from stellar_tpu.herder.quorum_intersection import (
            QuorumIntersectionChecker,
        )
        fickle = _fickle_qset(group, known)
        test = dict(known)
        for n in group:
            test[n] = fickle
        checker = QuorumIntersectionChecker(test)
        checker.max_calls = MAX_CHECKER_CALLS
        try:
            return not checker.network_enjoys_quorum_intersection()
        except TimeoutError:
            return False
