"""Network upgrades (reference ``src/herder/Upgrades.h`` / ``.cpp``).

A validator *schedules* upgrades (operator-set parameters + activation
time); at nomination it attaches the scheduled upgrades to its proposed
StellarValue, and every validator checks proposed upgrades twice:

* apply-validity (``isValidForApply``) — would this upgrade be legal on
  the current ledger at all (monotonic version, non-zero fee/reserve,
  protocol-gated arms, masked flags);
* nomination-validity (``isValidForNomination``) — does it exactly match
  what this node is scheduled to vote for, and is it time.

Ballot-phase validation uses only apply-validity, so a value carrying an
upgrade the node didn't schedule can still externalize — upgrades are
opt-in to *propose* but consensus to *apply*. Unknown/invalid upgrades
are validate-rejected here so ledger close never has to throw.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from stellar_tpu.protocol import (
    CURRENT_LEDGER_PROTOCOL_VERSION, SOROBAN_PROTOCOL_VERSION,
)
from stellar_tpu.xdr.ledger import LedgerUpgrade, LedgerUpgradeType
from stellar_tpu.xdr.runtime import from_bytes, to_bytes

__all__ = ["UpgradeParameters", "Upgrades", "MASK_LEDGER_HEADER_FLAGS",
           "UpgradeValidity"]

MASK_LEDGER_HEADER_FLAGS = 0x7  # the three DISABLE_LIQUIDITY_POOL_* bits

LUT = LedgerUpgradeType


class UpgradeValidity:
    VALID = 0
    XDR_INVALID = 1
    INVALID = 2


@dataclass
class UpgradeParameters:
    """Operator-scheduled upgrade vote (reference
    ``Upgrades::UpgradeParameters``)."""
    upgrade_time: int = 0  # unix time the vote activates
    protocol_version: Optional[int] = None
    base_fee: Optional[int] = None
    max_tx_set_size: Optional[int] = None
    base_reserve: Optional[int] = None
    flags: Optional[int] = None
    max_soroban_tx_set_size: Optional[int] = None
    config_upgrade_set_key: Optional[object] = None  # ConfigUpgradeSetKey


def config_upgrade_entry_key(key) -> bytes:
    """The contract-data location of a published ConfigUpgradeSet
    (reference SettingsUpgradeUtils: a TEMPORARY entry under
    key.contractID keyed by SCV_BYTES(contentHash))."""
    from stellar_tpu.ledger.ledger_txn import key_bytes
    from stellar_tpu.soroban.host import (
        contract_data_key, scaddress_contract, scbytes,
    )
    from stellar_tpu.xdr.contract import ContractDataDurability
    return key_bytes(contract_data_key(
        scaddress_contract(key.contractID), scbytes(key.contentHash),
        ContractDataDurability.TEMPORARY))


def load_config_upgrade_set(key, state_getter):
    """Load + hash-verify + parse the published ConfigUpgradeSet, or
    None (reference ``ConfigUpgradeSetFrame::makeFromKey``)."""
    from stellar_tpu.crypto.sha import sha256
    from stellar_tpu.xdr.contract import ConfigUpgradeSet, SCValType
    entry = state_getter(config_upgrade_entry_key(key))
    if entry is None:
        return None
    val = entry.data.value.val
    if val.arm != SCValType.SCV_BYTES:
        return None
    raw = val.value
    if sha256(raw) != key.contentHash:
        return None
    try:
        upgrade_set = from_bytes(ConfigUpgradeSet, raw)
    except Exception:
        return None
    if not upgrade_set.updatedEntry:
        return None
    # the bucket-list size window and eviction iterator are
    # core-maintained state that merely LIVES in CONFIG_SETTING
    # entries — an upgrade must never overwrite them (reference
    # SorobanNetworkConfig::isNonUpgradeableConfigSettingEntry,
    # src/ledger/NetworkConfig.cpp:1067-1082)
    from stellar_tpu.ledger.network_config import (
        NON_UPGRADEABLE_SETTING_IDS,
    )
    banned = NON_UPGRADEABLE_SETTING_IDS()
    if any(e.arm in banned for e in upgrade_set.updatedEntry):
        return None
    return upgrade_set


class Upgrades:
    def __init__(self, params: Optional[UpgradeParameters] = None,
                 max_protocol: int = CURRENT_LEDGER_PROTOCOL_VERSION):
        self.params = params or UpgradeParameters()
        # upgrades may carry any version up to what this build speaks;
        # the state-archival protocol became reachable once the hot
        # archive was header-committed and catchup-reconstructible
        # (p23 commitment + MINIMAL/replay reconstruction, r4)
        self.max_protocol = max_protocol

    # ---------------- validation ----------------

    def is_valid_for_apply(self, raw: bytes, header,
                           state_getter=None) -> int:
        """UpgradeValidity for one opaque upgrade against the current
        header (reference ``Upgrades::isValidForApply``).
        ``state_getter(kb) -> LedgerEntry|None`` gives CONFIG upgrades
        access to the published ConfigUpgradeSet entry."""
        try:
            up = from_bytes(LedgerUpgrade, bytes(raw))
        except Exception:
            return UpgradeValidity.XDR_INVALID
        version = header.ledgerVersion
        t = up.arm
        if t == LUT.LEDGER_UPGRADE_VERSION:
            ok = version < up.value <= self.max_protocol
        elif t == LUT.LEDGER_UPGRADE_BASE_FEE:
            ok = up.value != 0
        elif t == LUT.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            ok = True
        elif t == LUT.LEDGER_UPGRADE_BASE_RESERVE:
            ok = up.value != 0
        elif t == LUT.LEDGER_UPGRADE_FLAGS:
            ok = version >= 18 and \
                (up.value & ~MASK_LEDGER_HEADER_FLAGS) == 0
        elif t == LUT.LEDGER_UPGRADE_CONFIG:
            if version < SOROBAN_PROTOCOL_VERSION or state_getter is None:
                return UpgradeValidity.INVALID
            ok = load_config_upgrade_set(up.value, state_getter) \
                is not None
        elif t == LUT.LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE:
            ok = version >= SOROBAN_PROTOCOL_VERSION
        else:
            ok = False
        return UpgradeValidity.VALID if ok else UpgradeValidity.INVALID

    def _is_valid_for_nomination(self, up, close_time: int) -> bool:
        if self.params.upgrade_time > close_time:
            return False
        p = self.params
        t = up.arm
        if t == LUT.LEDGER_UPGRADE_VERSION:
            return p.protocol_version is not None and \
                up.value == p.protocol_version
        if t == LUT.LEDGER_UPGRADE_BASE_FEE:
            return p.base_fee is not None and up.value == p.base_fee
        if t == LUT.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            return p.max_tx_set_size is not None and \
                up.value == p.max_tx_set_size
        if t == LUT.LEDGER_UPGRADE_BASE_RESERVE:
            return p.base_reserve is not None and \
                up.value == p.base_reserve
        if t == LUT.LEDGER_UPGRADE_FLAGS:
            return p.flags is not None and up.value == p.flags
        if t == LUT.LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE:
            return p.max_soroban_tx_set_size is not None and \
                up.value == p.max_soroban_tx_set_size
        if t == LUT.LEDGER_UPGRADE_CONFIG:
            k = p.config_upgrade_set_key
            return k is not None and \
                up.value.contractID == k.contractID and \
                up.value.contentHash == k.contentHash
        return False

    def is_valid(self, raw: bytes, header, nomination: bool,
                 close_time: Optional[int] = None,
                 state_getter=None) -> bool:
        if self.is_valid_for_apply(raw, header, state_getter) != \
                UpgradeValidity.VALID:
            return False
        if nomination:
            up = from_bytes(LedgerUpgrade, bytes(raw))
            return self._is_valid_for_nomination(
                up, close_time if close_time is not None
                else header.scpValue.closeTime)
        return True

    # ---------------- proposal ----------------

    def create_upgrades_for(self, header, close_time: int,
                            soroban_config=None,
                            state_getter=None) -> List[bytes]:
        """The opaque upgrades this node votes for at nomination
        (reference ``Upgrades::createUpgradesFor``)."""
        if self.params.upgrade_time > close_time:
            return []
        p = self.params
        out = []
        if p.protocol_version is not None and \
                header.ledgerVersion != p.protocol_version:
            out.append(LedgerUpgrade.make(
                LUT.LEDGER_UPGRADE_VERSION, p.protocol_version))
        if p.base_fee is not None and header.baseFee != p.base_fee:
            out.append(LedgerUpgrade.make(
                LUT.LEDGER_UPGRADE_BASE_FEE, p.base_fee))
        if p.max_tx_set_size is not None and \
                header.maxTxSetSize != p.max_tx_set_size:
            out.append(LedgerUpgrade.make(
                LUT.LEDGER_UPGRADE_MAX_TX_SET_SIZE, p.max_tx_set_size))
        if p.base_reserve is not None and \
                header.baseReserve != p.base_reserve:
            out.append(LedgerUpgrade.make(
                LUT.LEDGER_UPGRADE_BASE_RESERVE, p.base_reserve))
        if p.flags is not None:
            cur = header.ext.value.flags if header.ext.arm == 1 else 0
            if cur != p.flags:
                out.append(LedgerUpgrade.make(
                    LUT.LEDGER_UPGRADE_FLAGS, p.flags))
        if p.max_soroban_tx_set_size is not None and (
                soroban_config is None or
                soroban_config.ledger_max_tx_count !=
                p.max_soroban_tx_set_size):
            out.append(LedgerUpgrade.make(
                LUT.LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE,
                p.max_soroban_tx_set_size))
        if p.config_upgrade_set_key is not None:
            # only nominate once the published ConfigUpgradeSet is
            # actually loadable — a vote armed before the publication
            # tx lands stays scheduled but silent (peers would reject
            # a value carrying an unloadable set)
            if state_getter is None or load_config_upgrade_set(
                    p.config_upgrade_set_key, state_getter) is not None:
                out.append(LedgerUpgrade.make(
                    LUT.LEDGER_UPGRADE_CONFIG, p.config_upgrade_set_key))
        return [to_bytes(LedgerUpgrade, u) for u in out]

    def _config_vote_done(self, soroban_config, state_getter) -> bool:
        """True when the scheduled CONFIG vote can be retired: the
        current network config already reflects the upgrade set (it
        applied). An unloadable set does NOT retire the vote — the
        publication may simply not have landed yet (create_upgrades_for
        stays silent until it does)."""
        import dataclasses
        from stellar_tpu.ledger.network_config import (
            apply_config_setting,
        )
        if state_getter is None or soroban_config is None:
            return False
        upgrade_set = load_config_upgrade_set(
            self.params.config_upgrade_set_key, state_getter)
        if upgrade_set is None:
            return False
        cfg = dataclasses.replace(soroban_config)
        try:
            for entry in upgrade_set.updatedEntry:
                apply_config_setting(cfg, entry)
        except ValueError:
            return True  # can never apply: malformed for this node
        return cfg == soroban_config

    def remove_upgrades_once_done(self, header, soroban_config=None,
                                  state_getter=None):
        """Clear votes that took effect (reference
        ``Upgrades::removeUpgrades`` after application)."""
        p = self.params
        if p.protocol_version is not None and \
                header.ledgerVersion >= p.protocol_version:
            p.protocol_version = None
        if p.base_fee is not None and header.baseFee == p.base_fee:
            p.base_fee = None
        if p.max_tx_set_size is not None and \
                header.maxTxSetSize == p.max_tx_set_size:
            p.max_tx_set_size = None
        if p.base_reserve is not None and \
                header.baseReserve == p.base_reserve:
            p.base_reserve = None
        if p.flags is not None:
            cur = header.ext.value.flags if header.ext.arm == 1 else 0
            if cur == p.flags:
                p.flags = None
        if p.max_soroban_tx_set_size is not None and \
                soroban_config is not None and \
                soroban_config.ledger_max_tx_count == \
                p.max_soroban_tx_set_size:
            p.max_soroban_tx_set_size = None
        if p.config_upgrade_set_key is not None and \
                self._config_vote_done(soroban_config, state_getter):
            p.config_upgrade_set_key = None
