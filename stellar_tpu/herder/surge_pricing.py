"""Multi-lane surge pricing (reference ``src/herder/SurgePricingUtils.h``
/ ``.cpp`` — ``SurgePricingLaneConfig`` + ``SurgePricingPriorityQueue``).

Transactions compete for block space by inclusion-fee *rate*; lanes put
independent ceilings on sub-classes of traffic (the reference ships a
DEX lane for classic and a generic lane for Soroban). Lane 0 is the
GENERIC lane whose limit is the whole capacity; limited lanes also count
against it. Selection pops the highest-fee-rate eligible head while
every account's sequence chain stays gapless; the per-lane base fee
under surge is the lowest included bid in that lane.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["SurgePricingLaneConfig", "SurgePricingPriorityQueue",
           "GENERIC_LANE"]

GENERIC_LANE = 0


class SurgePricingLaneConfig:
    """lane_limits[0] is total capacity; further entries cap specific
    lanes. ``lane_of`` classifies a frame; ``resources_of`` is its cost
    (ops for classic, tx count for Soroban)."""

    def __init__(self, lane_limits: List[int],
                 lane_of: Optional[Callable] = None,
                 resources_of: Optional[Callable] = None):
        self.lane_limits = lane_limits
        self._lane_of = lane_of or (lambda f: GENERIC_LANE)
        self._resources_of = resources_of or \
            (lambda f: max(1, f.num_operations()))

    def lane_of(self, frame) -> int:
        return self._lane_of(frame)

    def resources_of(self, frame) -> int:
        return self._resources_of(frame)


def _fee_rate_less_than(a, b) -> bool:
    return a.inclusion_fee() * b.num_operations() < \
        b.inclusion_fee() * a.num_operations()


class SurgePricingPriorityQueue:
    """Greedy top-bid selection under lane limits with gapless account
    chains (the ``getMostTopTxsWithinLimits`` role)."""

    @staticmethod
    def most_top_txs_within_limits(
            frames: Sequence, config: SurgePricingLaneConfig
    ) -> Tuple[List, List, Dict[int, bool]]:
        """(included, excluded, lane_was_full). Whole account tails are
        excluded on overflow so sequence numbers stay gapless."""
        queues: Dict[bytes, List] = {}
        for f in frames:
            queues.setdefault(f.source_account_id().value, []).append(f)
        for q in queues.values():
            q.sort(key=lambda f: f.seq_num)

        included: List = []
        excluded: List = []
        used = [0] * len(config.lane_limits)
        lane_full: Dict[int, bool] = {}
        heads = [(q[0], aid) for aid, q in queues.items()]
        while heads:
            best_i = 0
            for i in range(1, len(heads)):
                a, b = heads[i][0], heads[best_i][0]
                if _fee_rate_less_than(b, a) or (
                        not _fee_rate_less_than(a, b)
                        and a.contents_hash() < b.contents_hash()):
                    best_i = i
            frame, aid = heads.pop(best_i)
            q = queues[aid]
            lane = config.lane_of(frame)
            res = config.resources_of(frame)
            fits = used[GENERIC_LANE] + res <= \
                config.lane_limits[GENERIC_LANE]
            if lane != GENERIC_LANE and lane < len(config.lane_limits):
                fits = fits and \
                    used[lane] + res <= config.lane_limits[lane]
            if not fits:
                lane_full[lane] = True
                excluded.extend(q)
                queues[aid] = []
                continue
            used[GENERIC_LANE] += res
            if lane != GENERIC_LANE and lane < len(config.lane_limits):
                used[lane] += res
            included.append(frame)
            q.pop(0)
            if q:
                heads.append((q[0], aid))
        return included, excluded, lane_full

    @staticmethod
    def lane_base_fee(included: Sequence, default_base_fee: int,
                      surged: bool) -> int:
        """Lowest included per-op bid under surge, else the ledger base
        fee (reference ``computeLaneBaseFee``)."""
        if not surged or not included:
            return default_base_fee
        return min(f.inclusion_fee() // max(1, f.num_operations())
                   for f in included)
