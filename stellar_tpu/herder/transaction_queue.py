"""TransactionQueue: pre-consensus admission + per-account pending
chains (reference ``src/herder/TransactionQueue.h:44-137``).

Semantics kept: per-source-account sequence chains, fee-based
replace-by-fee (new tx must bid >= FEE_MULTIPLIER x the old), size
limiting in operations with lowest-fee eviction, ageing — a tx's account
is banned for ``BAN_LEDGERS`` ledgers when its txs sit unincluded for
``PENDING_DEPTH`` ledgers ("shift" per close).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

__all__ = ["TransactionQueue", "AddResult"]


class AddResult:
    ADD_STATUS_PENDING = 0
    ADD_STATUS_DUPLICATE = 1
    ADD_STATUS_ERROR = 2
    ADD_STATUS_TRY_AGAIN_LATER = 3
    ADD_STATUS_BANNED = 4
    ADD_STATUS_FILTERED = 5

    def __init__(self, code: int, tx_result=None):
        self.code = code
        self.tx_result = tx_result


FEE_MULTIPLIER = 10  # reference TransactionQueue::FEE_MULTIPLIER


class TransactionQueue:
    PENDING_DEPTH = 4   # ledgers a tx may age in the queue
    BAN_LEDGERS = 10    # reference default ban depth

    def __init__(self, max_ops: int,
                 check_valid: Callable,
                 pending_depth: int = PENDING_DEPTH,
                 ban_ledgers: int = BAN_LEDGERS,
                 excluded_op_types=frozenset()):
        self.max_ops = max_ops
        # (frame, current_seq) -> MutableTxResult; current_seq 0 means
        # "use the account's ledger seq"
        self.check_valid = check_valid
        self.pending_depth = pending_depth
        self.ban_ledgers = ban_ledgers
        # OperationType values refused at admission (reference
        # EXCLUDE_TRANSACTIONS_CONTAINING_OPERATION_TYPE)
        self.excluded_op_types = frozenset(excluded_op_types)
        # account raw key -> list of frames in seq order (+ age)
        self.accounts: Dict[bytes, List] = {}
        self.ages: Dict[bytes, int] = {}
        self.known_hashes: Dict[bytes, object] = {}
        self.banned: Dict[bytes, int] = {}  # tx hash -> ledgers left

    # ---------------- introspection ----------------

    def size_ops(self) -> int:
        return sum(max(1, f.num_operations())
                   for q in self.accounts.values() for f in q)

    def get_transactions(self) -> List:
        return [f for q in self.accounts.values() for f in q]

    def contains(self, frame) -> bool:
        return frame.contents_hash() in self.known_hashes

    # ---------------- admission ----------------

    def try_add(self, frame) -> AddResult:
        """Reference ``TransactionQueue::tryAdd``."""
        h = frame.contents_hash()
        if h in self.banned:
            return AddResult(AddResult.ADD_STATUS_BANNED)
        if h in self.known_hashes:
            return AddResult(AddResult.ADD_STATUS_DUPLICATE)
        if self.excluded_op_types:
            inner = getattr(frame, "inner", frame)
            if any(op.body.arm in self.excluded_op_types
                   for op in inner.tx.operations):
                return AddResult(AddResult.ADD_STATUS_FILTERED)

        acc = frame.source_account_id().value
        chain = self.accounts.get(acc, [])

        # validate against the predecessor's seq (the chain tail), not
        # the ledger's — chain extensions are the point of the queue
        current_seq = 0
        if chain and frame.seq_num == chain[-1].seq_num + 1:
            current_seq = chain[-1].seq_num
        res = self.check_valid(frame, current_seq)
        if not _ok(res):
            return AddResult(AddResult.ADD_STATUS_ERROR, res)

        # seq chain: must extend the chain or replace-by-fee an entry
        replaced = None
        if chain:
            last = chain[-1]
            if frame.seq_num == last.seq_num + 1:
                pass  # extends
            else:
                for i, old in enumerate(chain):
                    if old.seq_num == frame.seq_num:
                        if frame.full_fee() < \
                                old.full_fee() * FEE_MULTIPLIER:
                            return AddResult(
                                AddResult.ADD_STATUS_TRY_AGAIN_LATER)
                        replaced = i
                        break
                else:
                    return AddResult(AddResult.ADD_STATUS_TRY_AGAIN_LATER)

        # capacity: evict lowest-fee-rate tail or reject
        new_ops = max(1, frame.num_operations())
        if replaced is None and self.size_ops() + new_ops > self.max_ops:
            if not self._evict_for(frame, new_ops):
                return AddResult(AddResult.ADD_STATUS_TRY_AGAIN_LATER)

        if replaced is not None:
            old = chain[replaced]
            del self.known_hashes[old.contents_hash()]
            chain[replaced] = frame
        else:
            chain = self.accounts.setdefault(acc, chain)
            if not chain:
                self.accounts[acc] = chain
            chain.append(frame)
            self.ages.setdefault(acc, 0)
        self.known_hashes[h] = frame
        return AddResult(AddResult.ADD_STATUS_PENDING)

    def _evict_for(self, frame, need_ops: int) -> bool:
        """Evict strictly-lower-fee-rate txs to make room; False if the
        newcomer doesn't outbid anyone."""
        from stellar_tpu.herder.tx_set import fee_rate_less_than
        victims = []
        freed = 0
        # consider account tails with lower fee rate than the newcomer —
        # never the newcomer's own chain (evicting its predecessor would
        # orphan its sequence)
        self_acc = frame.source_account_id().value
        flat = [(q[-1], acc) for acc, q in self.accounts.items()
                if q and acc != self_acc]
        flat.sort(key=lambda t: t[0].inclusion_fee() /
                  max(1, t[0].num_operations()))
        for old, acc in flat:
            if not fee_rate_less_than(old, frame):
                break
            victims.append((old, acc))
            freed += max(1, old.num_operations())
            if self.size_ops() + need_ops - freed <= self.max_ops:
                for v, a in victims:
                    self._remove_tx(v, a)
                return True
        return False

    def _remove_tx(self, frame, acc: bytes):
        chain = self.accounts.get(acc, [])
        if frame in chain:
            # dropping mid-chain invalidates successors too
            i = chain.index(frame)
            for f in chain[i:]:
                self.known_hashes.pop(f.contents_hash(), None)
            del chain[i:]
        if not chain:
            self.accounts.pop(acc, None)
            self.ages.pop(acc, None)

    # ---------------- ledger-close bookkeeping ----------------

    def remove_applied(self, frames: List):
        """Drop txs included in a ledger; reset their accounts' age."""
        for f in frames:
            h = f.contents_hash()
            known = self.known_hashes.pop(h, None)
            acc = f.source_account_id().value
            chain = self.accounts.get(acc)
            if chain:
                kept = [x for x in chain
                        if x.seq_num > f.seq_num]
                for x in chain:
                    if x.seq_num <= f.seq_num and x is not known:
                        self.known_hashes.pop(x.contents_hash(), None)
                if kept:
                    self.accounts[acc] = kept
                else:
                    self.accounts.pop(acc, None)
                    self.ages.pop(acc, None)
            if acc in self.ages:
                self.ages[acc] = 0

    def shift(self):
        """Per-close ageing: old accounts' txs get banned + dropped
        (reference ``TransactionQueue::shift``)."""
        self.banned = {h: n - 1 for h, n in self.banned.items() if n > 1}
        for acc in list(self.accounts):
            self.ages[acc] = self.ages.get(acc, 0) + 1
            if self.ages[acc] >= self.pending_depth:
                for f in self.accounts[acc]:
                    h = f.contents_hash()
                    self.known_hashes.pop(h, None)
                    self.banned[h] = self.ban_ledgers
                self.accounts.pop(acc)
                self.ages.pop(acc)

    def is_banned(self, tx_hash: bytes) -> bool:
        return tx_hash in self.banned


def _ok(res) -> bool:
    from stellar_tpu.xdr.results import TransactionResultCode as TC
    return res.code in (TC.txSUCCESS, TC.txFEE_BUMP_INNER_SUCCESS)
