"""Static analysis for the TPU verify kernel and its host dispatch layer.

Three provers/linters, one CLI (``tools/analyze.py``), one tier-1 gate
(``tests/test_analysis.py`` + the ``tools/tier1.sh`` wiring):

* :mod:`stellar_tpu.analysis.intervals` /
  :mod:`stellar_tpu.analysis.overflow` — abstract interpretation with an
  interval domain over the traced jaxprs of the three verify-kernel
  stages, proving every integer intermediate fits its dtype with the
  carry headroom the limb layout assumes (``docs/kernel_design.md`` §1).
  The proven per-stage envelope is committed as ``docs/limb_bounds.json``
  so kernel PRs diff the proof, not just a pass/fail bit.
* :mod:`stellar_tpu.analysis.hotpath` — AST lint for host↔device sync
  hazards and retrace hazards in jit-adjacent code.
* :mod:`stellar_tpu.analysis.locks` — AST lint for shared mutable state
  mutated outside a ``with <lock>`` block in the threaded modules.
* :mod:`stellar_tpu.analysis.nondet` — the consensus nondeterminism lint
  (formerly inline in ``tests/test_nondet_lint.py``), on the shared
  framework, extended over the crypto host-oracle modules.

How to read a failure and how to extend an allowlist:
``docs/static_analysis.md``.
"""

from stellar_tpu.analysis.lint_base import Finding  # noqa: F401
