"""Overflow prover for the verify kernel's three stages.

Traces the same stage split as ``tools/kernel_cost.py`` —
``decompress`` / ``dsm`` / ``compress_compare`` plus the composed
``kernel_total`` — and abstract-interprets each jaxpr with the interval
domain (:mod:`stellar_tpu.analysis.intervals`), proving:

1. **dtype fit**: every integer intermediate's exact-arithmetic interval
   stays inside its dtype (int32 for limbs — the
   ``NLIMBS * LOOSE_MAX^2 < 2^31`` headroom claim of
   ``docs/kernel_design.md`` §1, per equation, not per comment);
2. **loose contract**: every limb-shaped stage *output* stays inside
   ``[0, LOOSE_MAX]`` — the inter-stage contract that makes the per-stage
   proofs compose (dsm consumes decompress's point, compress_compare
   consumes dsm's) and that the next field multiply's headroom assumes.

Input contracts per stage (supersets of what the composed kernel feeds):

* ``decompress``: ``(batch, 32)`` uint8 bytes in ``[0, 255]``;
* ``dsm``: scalar bytes in ``[0, 255]`` plus an extended point whose
  limbs are anywhere in the loose range ``[0, LOOSE_MAX]`` — the proof
  therefore covers *any* loose point, not just decompress outputs;
* ``compress_compare``: a loose point plus encoded bytes;
* ``kernel_total``: raw bytes end-to-end (validates the actual
  composition, including ``negate`` between decompress and dsm).

The proven per-stage envelope is summarized per limb (batch axes
collapse — bounds are batch-uniform, asserted across bucket sizes) and
committed as ``docs/limb_bounds.json`` so future kernel PRs diff the
proof itself, not just a pass/fail bit. ``bench.py`` embeds the
envelope's sha256 so a bench record can't come from an unproven kernel.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from stellar_tpu.analysis.intervals import (
    AbsVal, IntervalInterpreter, Unsupported,
)

__all__ = [
    "DEFAULT_BUCKETS", "STAGE_OUTPUT_NAMES", "prove", "prove_buckets",
    "envelope_sha256", "analyze_closed_jaxpr", "trace_stage_jaxprs",
    "loose_point_avals", "GOLDEN_PATH",
    "SHA_GOLDEN_PATH", "prove_sha256", "prove_sha256_buckets",
    "trace_sha256_jaxpr", "sha_default_buckets",
]

def _default_buckets():
    # the jit bucket cache sizes of the production verifier — the
    # shapes that actually compile and run, hence the shapes the proof
    # must cover (single source of truth in batch_verifier)
    from stellar_tpu.crypto.batch_verifier import DEFAULT_BUCKET_SIZES
    return DEFAULT_BUCKET_SIZES


DEFAULT_BUCKETS = _default_buckets()

GOLDEN_PATH = "docs/limb_bounds.json"

STAGE_OUTPUT_NAMES = {
    "decompress": ("ok", "x", "y", "z", "t"),
    "dsm": ("x", "y", "z"),
    "dsm_hot": ("x", "y", "z"),
    "compress_compare": ("ok",),
    "kernel_total": ("ok",),
    "kernel_hot_total": ("ok",),
}

# Limb-shaped stage outputs that must honor the loose contract
# [0, LOOSE_MAX]: the inter-stage composition invariant.
LOOSE_OUTPUTS = {
    "decompress": ("x", "y", "z", "t"),
    "dsm": ("x", "y", "z"),
    "dsm_hot": ("x", "y", "z"),
    "compress_compare": (),
    "kernel_total": (),
    "kernel_hot_total": (),
}


def _fe():
    from stellar_tpu.ops import field25519 as fe
    return fe


def loose_point_avals(batch: int):
    import jax
    fe = _fe()
    limb = jax.ShapeDtypeStruct((fe.NLIMBS, batch), np.int32)
    return (limb, limb, limb, limb)


def hot_table_aval(batch: int):
    """Aval of the cached per-signer affine table operand: batch-leading
    (batch, 128 entries, 3 coords, 20 limbs) int16 — host-canonical
    limbs, so every element is in [0, MASK]."""
    import jax
    from stellar_tpu.ops import edwards as ed
    fe = _fe()
    return jax.ShapeDtypeStruct(
        (batch, ed.TABLE_ENTRIES256, ed.AFFINE_COORDS, fe.NLIMBS),
        np.int16)


def trace_stage_jaxprs(batch: int) -> Dict[str, object]:
    """Trace the three stages + composed kernel (the kernel_cost split)."""
    import jax
    from stellar_tpu.ops import edwards as ed
    from stellar_tpu.ops import verify as vk

    bytes32 = jax.ShapeDtypeStruct((batch, 32), np.uint8)
    point = loose_point_avals(batch)
    hot_table = hot_table_aval(batch)

    def dsm(s_bytes, h_bytes, x, y, z, t):
        return vk.dsm_stage(s_bytes, h_bytes, (x, y, z, t))

    return {
        "decompress": jax.make_jaxpr(ed.decompress)(bytes32),
        "dsm": jax.make_jaxpr(dsm)(bytes32, bytes32, *point),
        "dsm_hot": jax.make_jaxpr(vk.dsm_stage_hot)(
            bytes32, bytes32, hot_table),
        "compress_compare": jax.make_jaxpr(
            lambda x, y, z, t, r: ed.compress_equals((x, y, z, t), r))(
                *point, bytes32),
        "kernel_total": jax.make_jaxpr(vk.verify_kernel)(
            bytes32, bytes32, bytes32, bytes32),
        "kernel_hot_total": jax.make_jaxpr(vk.verify_kernel_hot)(
            hot_table, bytes32, bytes32, bytes32),
    }


def _stage_invals(stage: str, batch: int) -> List[AbsVal]:
    import jax
    fe = _fe()
    bytes32 = jax.ShapeDtypeStruct((batch, 32), np.uint8)
    limb = jax.ShapeDtypeStruct((fe.NLIMBS, batch), np.int32)

    def byte_val():
        return AbsVal.from_range(bytes32, 0, 255)

    def limb_val():
        return AbsVal.from_range(limb, 0, fe.LOOSE_MAX)

    def table_val():
        # Cached signer tables are host-built with CANONICAL limbs
        # (parallel/signer_tables.py packs fe.from_int output), so the
        # operand contract is [0, MASK], tighter than the loose limbs
        # the in-kernel cold build feeds its selects.
        return AbsVal.from_range(hot_table_aval(batch), 0, fe.MASK)

    if stage == "decompress":
        return [byte_val()]
    if stage == "dsm":
        return [byte_val(), byte_val()] + [limb_val() for _ in range(4)]
    if stage == "dsm_hot":
        return [byte_val(), byte_val(), table_val()]
    if stage == "compress_compare":
        return [limb_val() for _ in range(4)] + [byte_val()]
    if stage == "kernel_total":
        return [byte_val() for _ in range(4)]
    if stage == "kernel_hot_total":
        return [table_val()] + [byte_val() for _ in range(3)]
    raise ValueError(stage)


def _ladder_hints() -> List[int]:
    fe = _fe()
    return [fe.MASK, fe.MASK + 1, fe.LOOSE_MAX, fe.FOLD,
            2 * fe.LOOSE_MAX, 4 * fe.LOOSE_MAX]


def _summarize_output(val: AbsVal) -> list:
    """Collapse batch axes: (20, batch) -> 20 [lo, hi] pairs; scalar or
    (batch,) bool -> one [lo, hi] pair."""
    fe = _fe()
    lo, hi = val.lo, val.hi
    if len(val.shape) >= 1 and val.shape[0] == fe.NLIMBS and \
            val.dtype == np.int32:
        axes = tuple(range(1, lo.ndim))
        llo = lo.min(axis=axes) if axes else lo
        lhi = hi.max(axis=axes) if axes else hi
        llo = np.broadcast_to(llo, (fe.NLIMBS,))
        lhi = np.broadcast_to(lhi, (fe.NLIMBS,))
        return [[int(a), int(b)] for a, b in zip(llo, lhi)]
    return [[int(lo.min()) if lo.size else 0,
             int(hi.max()) if hi.size else 0]]


def analyze_closed_jaxpr(closed_jaxpr, invals: Sequence[AbsVal],
                         stage: str = "jaxpr") -> dict:
    """Run the interval interpreter over one traced stage; returns
    ``{violations, max_abs, outputs}`` (outputs as AbsVals)."""
    interp = IntervalInterpreter(ladder_hints=_ladder_hints())
    outs = interp.eval_closed(closed_jaxpr, invals, path=stage)
    return {
        "violations": interp.violations,
        "max_abs": interp.max_abs,
        "outputs": outs,
    }


def prove(batch: int) -> dict:
    """Prove all four stage jaxprs at one batch size. Returns a record
    with ``ok``, per-stage envelopes, violations, and contract breaches."""
    fe = _fe()
    jaxprs = trace_stage_jaxprs(batch)
    stages = {}
    violations: List[dict] = []
    contract: List[str] = []
    unsupported: List[str] = []
    for stage, jx in jaxprs.items():
        try:
            res = analyze_closed_jaxpr(jx, _stage_invals(stage, batch),
                                       stage)
        except Unsupported as e:
            unsupported.append(str(e))
            stages[stage] = {"max_abs": None, "outputs": {}}
            continue
        names = STAGE_OUTPUT_NAMES[stage]
        outs = res["outputs"]
        if len(names) != len(outs):
            unsupported.append(
                f"{stage}: expected {len(names)} outputs, traced "
                f"{len(outs)} — stage split drifted, update "
                "STAGE_OUTPUT_NAMES")
            continue
        out_summ = {n: _summarize_output(v) for n, v in zip(names, outs)}
        stages[stage] = {"max_abs": int(res["max_abs"]),
                         "outputs": out_summ}
        violations.extend(v.to_dict() for v in res["violations"])
        for name in LOOSE_OUTPUTS[stage]:
            for limb, (lo, hi) in enumerate(out_summ[name]):
                if lo < 0 or hi > fe.LOOSE_MAX:
                    contract.append(
                        f"{stage}.{name} limb {limb} in [{lo}, {hi}] "
                        f"escapes the loose contract [0, {fe.LOOSE_MAX}]"
                        " — the next stage's multiply headroom is gone")
    envelope = {
        "format": 1,
        "limb_layout": {"nlimbs": fe.NLIMBS, "bits": fe.BITS,
                        "mask": fe.MASK, "loose_max": fe.LOOSE_MAX,
                        "fold": fe.FOLD},
        "stages": stages,
    }
    return {
        "batch": batch,
        "ok": not violations and not contract and not unsupported,
        "violations": violations,
        "contract_breaches": contract,
        "unsupported": unsupported,
        "envelope": envelope,
        "envelope_sha256": envelope_sha256(envelope),
    }


def prove_buckets(buckets: Sequence[int] = DEFAULT_BUCKETS) -> dict:
    """Prove at every jit bucket size; the envelope must be identical
    across buckets (bounds are batch-uniform — a difference means the
    kernel's math depends on batch size, itself a red flag)."""
    records = [prove(b) for b in buckets]
    first = records[0]
    mismatch = [
        r["batch"] for r in records[1:]
        if r["envelope_sha256"] != first["envelope_sha256"]]
    out = dict(first)
    out["buckets"] = list(buckets)
    out["ok"] = all(r["ok"] for r in records) and not mismatch
    out["envelope_mismatch_buckets"] = mismatch
    # merge EVERY failure class across buckets (tagged with the bucket
    # that produced it): a later bucket failing with a clean first
    # bucket must still explain itself in the gate output
    for r in records[1:]:
        out["violations"] = out["violations"] + [
            v for v in r["violations"] if v not in out["violations"]]
        for key in ("contract_breaches", "unsupported"):
            out[key] = out[key] + [
                f"[batch={r['batch']}] {m}"
                for m in r[key] if m not in out[key]]
    return out


# ---------------- SHA-256 workload proof (ISSUE 7) ----------------
# Workload #2 on the batch substrate gets the same treatment as the
# verify kernel: interval-prove every integer intermediate fits its
# dtype at every jit bucket size, and commit the proven envelope as a
# golden so future kernel PRs diff the proof itself. The interesting
# obligations here are the masked half-word adds (each half-lane sum
# must stay inside uint32 — a dropped mask would surface immediately)
# and the pre-masked rotations (the left-shift operand must be
# provably < 2^32). Separate golden file: the ed25519 envelope
# (docs/limb_bounds.json) is pinned unchanged by the ISSUE 7
# acceptance criteria.

SHA_GOLDEN_PATH = "docs/sha256_bounds.json"


def sha_default_buckets():
    from stellar_tpu.crypto.batch_hasher import DEFAULT_HASH_BUCKET_SIZES
    return DEFAULT_HASH_BUCKET_SIZES


def _sha_max_blocks():
    from stellar_tpu.crypto.batch_hasher import MAX_BLOCKS
    return MAX_BLOCKS


def trace_sha256_jaxpr(batch: int, max_blocks: Optional[int] = None):
    import jax
    from stellar_tpu.ops import sha256 as sk
    max_blocks = max_blocks or _sha_max_blocks()
    words = jax.ShapeDtypeStruct((batch, max_blocks, 16), np.uint32)
    active = jax.ShapeDtypeStruct((batch, max_blocks), np.bool_)
    return jax.make_jaxpr(sk.sha256_kernel)(words, active)


def prove_sha256(batch: int, max_blocks: Optional[int] = None) -> dict:
    """Prove the SHA-256 kernel at one bucket size: full-range uint32
    message words, any active-block mask. One stage ("sha256_kernel"),
    one output (the digest words, which must span exactly uint32)."""
    max_blocks = max_blocks or _sha_max_blocks()
    jaxpr = trace_sha256_jaxpr(batch, max_blocks)
    words = AbsVal.from_range(
        type("A", (), {"shape": (batch, max_blocks, 16),
                       "dtype": np.uint32})(), 0, 0xFFFFFFFF)
    active = AbsVal.from_range(
        type("A", (), {"shape": (batch, max_blocks),
                       "dtype": np.bool_})(), 0, 1)
    violations: List[dict] = []
    unsupported: List[str] = []
    stages = {}
    try:
        res = analyze_closed_jaxpr(jaxpr, [words, active],
                                   "sha256_kernel")
        out, = res["outputs"]
        lo = int(out.lo.min()) if out.lo.size else 0
        hi = int(out.hi.max()) if out.hi.size else 0
        stages["sha256_kernel"] = {
            "max_abs": int(res["max_abs"]),
            "outputs": {"digest": [[lo, hi]]},
        }
        violations = [v.to_dict() for v in res["violations"]]
    except Unsupported as e:
        unsupported.append(str(e))
        stages["sha256_kernel"] = {"max_abs": None, "outputs": {}}
    envelope = {
        "format": 1,
        "word_layout": {"word_bits": 32, "max_blocks": int(max_blocks),
                        "rounds": 64},
        "stages": stages,
    }
    return {
        "batch": batch,
        "ok": not violations and not unsupported,
        "violations": violations,
        "contract_breaches": [],
        "unsupported": unsupported,
        "envelope": envelope,
        "envelope_sha256": envelope_sha256(envelope),
    }


def prove_sha256_buckets(buckets: Optional[Sequence[int]] = None,
                         max_blocks: Optional[int] = None) -> dict:
    """Prove the SHA-256 kernel at every hash jit bucket size; the
    envelope must be identical across buckets (same batch-uniformity
    argument as ``prove_buckets``)."""
    buckets = list(buckets or sha_default_buckets())
    records = [prove_sha256(b, max_blocks) for b in buckets]
    first = records[0]
    mismatch = [
        r["batch"] for r in records[1:]
        if r["envelope_sha256"] != first["envelope_sha256"]]
    out = dict(first)
    out["buckets"] = buckets
    out["ok"] = all(r["ok"] for r in records) and not mismatch
    out["envelope_mismatch_buckets"] = mismatch
    for r in records[1:]:
        out["violations"] = out["violations"] + [
            v for v in r["violations"] if v not in out["violations"]]
        out["unsupported"] = out["unsupported"] + [
            f"[batch={r['batch']}] {m}"
            for m in r["unsupported"] if m not in out["unsupported"]]
    return out


def load_sha_golden(repo_root: str) -> Optional[dict]:
    import os
    path = os.path.join(repo_root, SHA_GOLDEN_PATH)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def envelope_sha256(envelope: dict) -> str:
    canon = json.dumps(envelope, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def load_golden(repo_root: str) -> Optional[dict]:
    import os
    path = os.path.join(repo_root, GOLDEN_PATH)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def diff_golden(envelope: dict, golden: dict) -> List[str]:
    """Human-readable envelope-vs-golden differences (empty = match)."""
    diffs: List[str] = []
    if golden.get("limb_layout") != envelope.get("limb_layout"):
        diffs.append(
            f"limb_layout changed: {golden.get('limb_layout')} -> "
            f"{envelope.get('limb_layout')}")
    gst = golden.get("stages", {})
    est = envelope.get("stages", {})
    for stage in sorted(set(gst) | set(est)):
        g, e = gst.get(stage), est.get(stage)
        if g is None or e is None:
            diffs.append(f"stage {stage}: "
                         f"{'added' if g is None else 'removed'}")
            continue
        if g.get("max_abs") != e.get("max_abs"):
            diffs.append(f"{stage}.max_abs: {g.get('max_abs')} -> "
                         f"{e.get('max_abs')}")
        go, eo = g.get("outputs", {}), e.get("outputs", {})
        for name in sorted(set(go) | set(eo)):
            if go.get(name) != eo.get(name):
                diffs.append(f"{stage}.{name}: {go.get(name)} -> "
                             f"{eo.get(name)}")
    return diffs
