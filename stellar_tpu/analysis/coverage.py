"""Proof-coverage gate: every engine kernel variant carries a proof.

The interval prover (``analysis/overflow.py``) proves per-stage
overflow envelopes and pins them in committed goldens
(``docs/limb_bounds.json`` / ``docs/sha256_bounds.json``) — but
nothing forced a NEW kernel variant to show up there. PR 16's
``verify_kernel_hot`` carried its proof because a human remembered;
the ROADMAP's BLS/MSM workloads would ship unproven by default. This
gate closes that: it enumerates every ``Workload`` plugin registered
with the engine (cold, hot, sha256, and any future subclass) and
asserts each maps to a proven envelope stage in a committed golden —
an unproven kernel fails ``tools/analyze.py`` instead of shipping.

Coverage is keyed ``(metrics_ns, variant_name)`` — the same pair the
engine uses to key a plugin's jit wrappers, so a variant cannot reach
the dispatch tier without also being visible here. A new workload
joins by proving its stages (``tools/analyze.py --write-golden``
style) and adding its mapping to :data:`PROOF_STAGES`; the gate makes
forgetting either a hard failure, not a review comment.
"""

from __future__ import annotations

import importlib
import json
from typing import Dict, List, Optional, Tuple

from stellar_tpu.analysis.lint_base import (
    Allowlist, Finding, finish_report, repo_root,
)

__all__ = ["run", "check", "enumerate_kernels", "PROOF_STAGES",
           "PLUGIN_MODULES", "ALLOWLIST"]

#: modules whose import registers Workload subclasses with the engine
PLUGIN_MODULES = [
    "stellar_tpu.crypto.batch_verifier",
    "stellar_tpu.crypto.batch_hasher",
]

#: (metrics_ns, variant_name) -> (committed golden, proven stage)
PROOF_STAGES: Dict[Tuple[str, Optional[str]], Tuple[str, str]] = {
    ("crypto.verify", None): ("docs/limb_bounds.json",
                              "kernel_total"),
    ("crypto.verify", "hot"): ("docs/limb_bounds.json",
                               "kernel_hot_total"),
    ("crypto.hash", None): ("docs/sha256_bounds.json",
                            "sha256_kernel"),
}

# No entries by design: an unproven kernel is fixed by PROVING it, not
# by arguing it away — the Allowlist exists only so the stale sweep
# and report wiring stay uniform across every gate family.
ALLOWLIST = Allowlist({})


def enumerate_kernels() -> List[Tuple[str, Optional[str], str]]:
    """Every kernel variant registered with the engine:
    ``(metrics_ns, variant_name, class name)``, base class excluded,
    sorted for stable reports."""
    from stellar_tpu.parallel import batch_engine
    for mod in PLUGIN_MODULES:
        importlib.import_module(mod)

    def walk(cls):
        for sub in cls.__subclasses__():
            yield sub
            yield from walk(sub)

    out = []
    for cls in walk(batch_engine.Workload):
        # only shipped kernels are gated — Workload subclasses defined
        # by test modules are fixtures, not dispatchable variants
        mod = cls.__module__ or ""
        if not (mod == "stellar_tpu" or mod.startswith("stellar_tpu.")):
            continue
        out.append((cls.metrics_ns, cls.variant_name, cls.__name__))
    return sorted(out, key=lambda k: (k[0], k[1] or "", k[2]))


def _load_goldens(root) -> Dict[str, Optional[dict]]:
    goldens: Dict[str, Optional[dict]] = {}
    for _ns_variant, (rel, _stage) in PROOF_STAGES.items():
        if rel in goldens:
            continue
        path = root / rel
        if not path.exists():
            goldens[rel] = None
            continue
        try:
            goldens[rel] = json.loads(path.read_text())
        except (ValueError, OSError):
            goldens[rel] = None
    return goldens


def check(kernels: List[Tuple[str, Optional[str], str]],
          goldens: Dict[str, Optional[dict]],
          proof_stages: Optional[dict] = None
          ) -> Tuple[List[Finding], List[dict]]:
    """Pure coverage check (unit-test hook): returns (findings, one
    row per kernel). A kernel is proven iff its ``(ns, variant)`` maps
    to a stage present, with a recorded envelope, in a loaded golden."""
    stages = PROOF_STAGES if proof_stages is None else proof_stages
    findings: List[Finding] = []
    rows: List[dict] = []
    for ns, variant, cname in kernels:
        row = {"metrics_ns": ns, "variant": variant, "class": cname,
               "proven": False, "golden": None, "stage": None}
        mapped = stages.get((ns, variant))
        if mapped is None:
            findings.append(Finding(
                file="stellar_tpu/analysis/coverage.py", line=1,
                rule="proof-coverage",
                symbol=f"{ns}:{variant or 'cold'}",
                message=f"kernel variant {cname} ({ns}, "
                        f"variant={variant!r}) has no proven "
                        "overflow-envelope stage mapped in "
                        "coverage.PROOF_STAGES — prove its stages "
                        "and commit the golden before shipping"))
            rows.append(row)
            continue
        rel, stage = mapped
        row["golden"], row["stage"] = rel, stage
        golden = goldens.get(rel)
        entry = (golden or {}).get("stages", {}).get(stage)
        if not entry or "max_abs" not in entry:
            findings.append(Finding(
                file=rel, line=1, rule="proof-coverage",
                symbol=f"{ns}:{variant or 'cold'}",
                message=f"kernel variant {cname} maps to stage "
                        f"{stage!r} but the committed golden {rel} "
                        "has no proven envelope for it — re-run "
                        "tools/analyze.py --write-golden after "
                        "proving the stage"))
            rows.append(row)
            continue
        row["proven"] = True
        rows.append(row)
    return findings, rows


def run(allowlist: Optional[Allowlist] = None) -> dict:
    """The gate over the real engine + committed goldens. Returns a
    LintReport dict plus the per-kernel rows (``kernels``) and the
    proven count (``proven``) for the tier-1 echo."""
    root = repo_root()
    kernels = enumerate_kernels()
    findings, rows = check(kernels, _load_goldens(root))
    rep = finish_report("proof_coverage", len(kernels), findings,
                        allowlist or ALLOWLIST)
    out = rep.to_dict()
    out["kernels"] = rows
    out["proven"] = sum(1 for r in rows if r["proven"])
    return out
