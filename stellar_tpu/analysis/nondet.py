"""Consensus nondeterminism lint, on the shared lint framework.

Reference ``src/test/check-nondet``: a CI grep banning ``std::rand`` /
unseeded randomness from consensus code. The consensus-critical packages
must not consult wall clocks, unseeded RNGs, or per-process hash salts —
any of those is a consensus-divergence hazard between nodes.

This PR moves the pass out of ``tests/test_nondet_lint.py`` (which now
just drives it) onto the shared framework — same file walking, same
allowlist format with mandatory written safety arguments, same JSON
report through ``tools/analyze.py`` — and extends coverage to the
``stellar_tpu/crypto`` host-oracle modules: the failover path re-verifies
signatures through these (``docs/robustness.md`` — "degraded mode changes
latency, never decisions"), so their decisions must be exactly as
deterministic as the consensus packages'.
"""

from __future__ import annotations

import re
from typing import List, Optional

from stellar_tpu.analysis.lint_base import (
    Allowlist, Finding, LintReport, finish_report, repo_root, walk_py,
)
# quote-aware '#' stripping: a '#' inside a string literal must not
# truncate the line before a banned call that follows it
from stellar_tpu.utils.toml_compat import _strip_comment

__all__ = ["run", "lint_source", "drift_findings", "CONSENSUS_DIRS",
           "HOST_ORACLE_FILES", "DRIFT_ROOTS", "ALLOWLIST", "BANNED",
           "TRACING_SANCTIONED"]

# packages whose behavior must be bit-identical across nodes
CONSENSUS_DIRS = ["stellar_tpu/scp", "stellar_tpu/ledger",
                  "stellar_tpu/tx", "stellar_tpu/bucket",
                  "stellar_tpu/soroban", "stellar_tpu/xdr"]

# crypto host-oracle modules: the host half of every verify decision
# (policy gates, SHA-512 prep, the failover oracle) plus the pure
# primitives under them — one nondeterministic branch here and the
# device and host halves of a verdict could disagree
HOST_ORACLE_FILES = [
    # the result-integrity audit sampler and the quarantine registry:
    # both gate WHICH backend serves a consensus verdict — the sample
    # must be content-derived and the quarantine logic clock/RNG-free,
    # or replicas could diverge in what they re-verify
    "stellar_tpu/crypto/audit.py",
    "stellar_tpu/parallel/device_health.py",
    # the resident verify service decides WHICH queued work gets
    # verified vs shed under overload — the shed rule must stay
    # content-seeded (audit.keep_under_shed) and the scheduler
    # sequence-based, never clocked or RNG-driven
    "stellar_tpu/crypto/verify_service.py",
    # the tenant QoS layer (ISSUE 14): per-tenant quotas, the
    # weighted-fair scheduler's virtual-time accounting, and the
    # tenant-keyed shed fractions all decide WHICH tenant's work
    # dispatches or sheds — pure integer/content arithmetic, zero
    # clock reads, NO allowlist entry (pinned in test_analysis.py)
    "stellar_tpu/crypto/tenant.py",
    # the closed-loop controller (ISSUE 15): its decisions move the
    # service's scheduling knobs (batch size, pipeline depth, shed
    # highwater), so it must be a pure function of the telemetry
    # window it is handed — zero clock reads, NO allowlist entry
    # (pinned in test_analysis.py), or two replicas' knob
    # trajectories could diverge under identical inputs
    "stellar_tpu/crypto/controller.py",
    # the fleet router (ISSUE 17): routing, probation re-admission
    # and divergence conviction all decide WHICH replica serves a
    # submission — pure SHA-256 rendezvous draws over event-count
    # state, zero clock reads, NO allowlist entry (pinned in
    # test_analysis.py), or two independently constructed routers
    # could route the same stream differently (the per-replica
    # breakers keep their clocks inside resilience.py; they are a
    # metric surface, never a routing input)
    "stellar_tpu/crypto/fleet.py",
    # the wire ingress + frame codec (ISSUE 19): what arrived, what
    # was malformed, what was refused and which trace block each
    # frame got must be pure functions of the byte stream — NO
    # allowlist entry (pinned in test_analysis.py), so read deadlines
    # ride socket timeouts and event counts, never a clock read, and
    # two nodes decoding the same bytes always agree
    "stellar_tpu/crypto/ingress.py",
    "stellar_tpu/utils/wire.py",
    # the unified system journal (ISSUE 20): merge order, the
    # completeness residual and the canonical bytes must be pure
    # functions of the logs they are handed — one clock or RNG draw
    # and two replicas' merged journals could differ while both are
    # honest, which is exactly the divergence the merge is built to
    # convict. NO allowlist entry (pinned in test_analysis.py)
    "stellar_tpu/utils/journal.py",
    # the workload-agnostic batch engine owns dispatch, re-shard,
    # audit-sample composition, and host-oracle failover for EVERY
    # plugin — a clock or RNG here would desynchronize which rows any
    # replica audits or sheds, for all workloads at once
    "stellar_tpu/parallel/batch_engine.py",
    # the SHA-256 workload: kernel host helpers (padding/encode) and
    # the hasher plugin feed bucket-list and catchup hashes that must
    # be bit-identical across nodes
    "stellar_tpu/ops/sha256.py",
    "stellar_tpu/crypto/batch_hasher.py",
    # the transfer ledger records every engine upload/fetch and the
    # perf sentinel gates bench-record drift in tier-1: both must stay
    # clock/RNG-free — fingerprints and drift verdicts are
    # content-derived, so two runs over the same bytes always agree
    "stellar_tpu/utils/transfer_ledger.py",
    "tools/perf_sentinel.py",
    # the device-resident constant cache (ISSUE 12) decides which
    # operand uploads are skipped: keys must be content-derived and
    # eviction clock/RNG-free, or replicas could pin different buffers
    # (a latency divergence only — but the discipline is free to keep)
    "stellar_tpu/parallel/residency.py",
    # the per-pubkey signer-table cache (ISSUE 16) decides which rows
    # dispatch HOT vs cold: keys must be content-derived and eviction
    # clock/RNG-free — verdicts are path-independent (pinned by the
    # differential suite), but the hot/cold split must still replay
    # identically or replicas' ledgers and audits drift apart
    "stellar_tpu/parallel/signer_tables.py",
    # the trickle batcher + verify collector (ISSUE 18 scope-drift
    # sweep): composes the reference oracle, native prep and the
    # signer-table partitioner into batch verdicts — its one clock
    # (trickle window pacing) decides WHEN a batch dispatches, never
    # what any row's verdict is (allowlisted below)
    "stellar_tpu/crypto/batch_verifier.py",
    # transport sealed boxes over curve25519 (ISSUE 18 scope-drift
    # sweep): pure HSalsa/HMAC composition, zero clock/RNG reads of
    # its own — NO allowlist entry (pinned in test_analysis.py)
    "stellar_tpu/crypto/nacl_box.py",
    "stellar_tpu/crypto/ed25519_ref.py",
    "stellar_tpu/crypto/curve25519.py",
    "stellar_tpu/crypto/keys.py",
    "stellar_tpu/crypto/native_prep.py",
    "stellar_tpu/crypto/native_verify.py",
    "stellar_tpu/crypto/sha.py",
    "stellar_tpu/crypto/keccak.py",
    "stellar_tpu/crypto/shorthash.py",
    "stellar_tpu/crypto/strkey.py",
    "stellar_tpu/crypto/secp256.py",
    "stellar_tpu/crypto/h2c.py",
    "stellar_tpu/crypto/bls12_381.py",
]

BANNED = [
    # (key, pattern, why)
    ("random", re.compile(
        r"\brandom\.(random|randint|randrange|choice|shuffle|"
        r"getrandbits)\b"),
     "unseeded process RNG in consensus code"),
    ("os.urandom", re.compile(r"\bos\.urandom\b"),
     "CSPRNG output must not influence consensus state"),
    ("secrets", re.compile(
        r"\bsecrets\.(token_bytes|randbits|randbelow)\b"),
     "CSPRNG output must not influence consensus state"),
    ("clock", re.compile(
        r"\btime\.time\(\)|\btime\.monotonic\(\)|"
        r"\btime\.perf_counter\(\)"),
     "wall/monotonic clock reads diverge between nodes"),
    ("wallclock", re.compile(
        r"\bdatetime\.now\(\)|\bdatetime\.utcnow\(\)"),
     "wall clock reads diverge between nodes"),
    # bare builtin hash( — NOT .hash() methods (content hashes)
    ("hash", re.compile(r"(?<![.\w])hash\("),
     "builtin hash() is salted per-process (PYTHONHASHSEED)"),
]

# ---------------- tracing fence (ISSUE 5) ----------------
# stellar_tpu/utils/tracing.py is clock-bearing BY DESIGN (perf_counter
# pairs, span records, the flight recorder). Consensus/host-oracle
# modules may use only its duration-blind context managers — zone/span
# etc. time a scope but never EXPOSE a duration to the caller, so their
# clock reads cannot influence a decision. Importing the module itself
# (or any other name, e.g. ``flight_recorder`` or ``span_totals``)
# would hand consensus code readable clock state and is banned.
TRACING_SANCTIONED = frozenset({
    "zone", "span", "LogSlowExecution", "current_zones", "frame_mark",
})

_TRACING_MODULE = re.compile(
    r"^\s*import\s+stellar_tpu\.utils\.tracing\b")
# from stellar_tpu.utils import a, (tracing), ... — names checked
# after paren accumulation, so the parenthesized spelling can't slip
# the module in
_UTILS_FROM = re.compile(
    r"^\s*from\s+stellar_tpu\.utils\s+import\s+(.*)$")
_TRACING_FROM = re.compile(
    r"^\s*from\s+stellar_tpu\.utils\.tracing\s+import\s+(.*)$")


def _lint_tracing_imports(text: str, rel: str) -> List[Finding]:
    """Fence tracing out of consensus modules: only the sanctioned
    duration-blind names may be imported. Handles parenthesized
    multi-line from-imports (the ``ledger_manager`` spelling)."""
    out: List[Finding] = []

    def emit(lineno: int, what: str):
        out.append(Finding(
            file=rel, line=lineno, rule="nondet", symbol="tracing-import",
            message=f"{what} — tracing is clock-bearing by design; "
                    "consensus modules may import only its "
                    "duration-blind context managers "
                    f"({', '.join(sorted(TRACING_SANCTIONED))})"))

    lines = text.splitlines()

    def gather_names(first: str, i: int) -> tuple:
        """Imported names of one from-import, accumulating BOTH
        continuation spellings — parenthesized and backslash-continued
        lines; returns (names, next_i)."""
        src = first
        while i + 1 < len(lines) and (
                ("(" in src and ")" not in src)
                or src.rstrip().endswith("\\")):
            i += 1
            src = src.rstrip().rstrip("\\") + " " + \
                _strip_comment(lines[i])
        names = [tok.split(" as ")[0].strip()
                 for tok in src.replace("(", " ").replace(")", " ")
                 .replace("\\", " ").split(",")]
        return [nm for nm in names if nm], i

    i = 0
    while i < len(lines):
        lineno = i + 1
        line = _strip_comment(lines[i])
        if _TRACING_MODULE.match(line):
            emit(lineno, "module-level tracing import")
            i += 1
            continue
        m = _TRACING_FROM.match(line)
        if m is not None:
            names, i = gather_names(m.group(1), i)
            bad = [nm for nm in names
                   if nm not in TRACING_SANCTIONED]
            if bad:
                emit(lineno, "import of non-sanctioned tracing "
                             f"names {bad}")
            i += 1
            continue
        m = _UTILS_FROM.match(line)
        if m is not None:
            names, i = gather_names(m.group(1), i)
            if "tracing" in names:
                emit(lineno, "module-level tracing import")
        i += 1
    return out

ALLOWLIST = Allowlist({
    # (the seed's allowlist carried a stale tx_test_utils.py entry for
    # secrets.token_bytes — the code it excused is gone; the framework
    # now fails on stale entries, which is how it surfaced)
    "stellar_tpu/crypto/keys.py": {
        "nondet:clock":
            "sign_ops_per_second/verify_ops_per_second mirror the "
            "reference's SecretKey::benchmarkOpsPerSecond "
            "(SecretKey.cpp:193-233): perf_counter pairs measuring a "
            "benchmark loop's own wall time, returned to operators/"
            "bench tooling only — no verify decision or ledger state "
            "ever reads them.",
        "nondet:os.urandom":
            "SecretKey.random()/PublicKey generation: key MATERIAL, "
            "not consensus state — randomness here is the whole point "
            "and never feeds a verify decision (decisions depend only "
            "on the resulting public bytes).",
        "nondet:random":
            "SecretKey.pseudo_random_for_testing mirrors the "
            "reference's test-only generator (SecretKey.h:66-77); "
            "test fixtures, never ledger state.",
    },
    "stellar_tpu/crypto/curve25519.py": {
        "nondet:os.urandom":
            "X25519 ephemeral keypair generation for transport "
            "encryption (overlay auth) — key material consumed only "
            "by the local handshake, never consensus state.",
    },
    "stellar_tpu/crypto/shorthash.py": {
        "nondet:os.urandom":
            "per-process siphash key, mirroring the reference's "
            "shortHash::initialize(): short hashes are process-local "
            "(hashmap seeding) and never cross the wire or enter "
            "consensus state.",
    },
    "stellar_tpu/crypto/verify_service.py": {
        "nondet:clock":
            "time.monotonic() stamps admission and completion for the "
            "per-lane wait-time histograms (the p50/p99 the soak "
            "harness publishes) and the SLO latency accounting that "
            "consumes the SAME stamp (burn rates feed dashboards "
            "only), and ages the adopter cool-down "
            "window (service_verified's wedged-dispatcher bypass). "
            "Neither reads decide a VERDICT: admission verdicts "
            "depend on bounded queue/byte budgets, scheduling order "
            "on priorities plus admission sequence numbers, WHICH "
            "rows shed on the content-seeded rule in crypto/audit.py "
            "(replicas under identical pressure shed identical rows), "
            "and the cool-down only picks WHICH bit-identical path "
            "serves a signature check (service lane vs direct "
            "verify_sig) — the differential gates pin both paths to "
            "the same bools, so a clock-driven bypass can never "
            "diverge replicas' consensus state.",
    },
    "stellar_tpu/crypto/batch_verifier.py": {
        "nondet:clock":
            "time.perf_counter() pairs pace the trickle-batch "
            "window (how long the leader waits for co-riders before "
            "dispatching) — the clock decides WHEN a batch goes to "
            "the device, never WHAT any row's verdict is: verdicts "
            "come from the device kernel or the host oracle, both "
            "pinned bit-identical by the differential gates, so "
            "window jitter can only move latency, not decisions.",
        "nondet:tracing-import":
            "the verify collector is an instrumentation owner like "
            "batch_engine: it opens collection/dispatch spans and "
            "notes trace events for the flight recorder — durations "
            "land in observability records only; verdict composition "
            "reads device/oracle bits, never a span reading.",
    },
    "stellar_tpu/parallel/batch_engine.py": {
        "nondet:clock":
            "time.monotonic() ages the device-probe thread (overdue "
            "probe accounting) — local liveness bookkeeping deciding "
            "only WHICH backend serves, never what a row's verdict "
            "is: device and host-oracle answers are pinned "
            "bit-identical by the differential gates and the sampled "
            "audit, so a clock-driven backend flip cannot diverge "
            "replicas' consensus state.",
        "nondet:tracing-import":
            "the engine IS the instrumentation owner the fence "
            "protects consensus code from: it opens the resolve-phase "
            "spans, dumps the flight recorder on breaker/quarantine/"
            "shed onsets, and feeds dispatch_attribution — durations "
            "land in observability records only, while row verdicts "
            "are composed from device/oracle bits plus the "
            "content-seeded audit sample, never a span reading.",
    },
})


def _lint_lines(text: str, rel: str) -> List[Finding]:
    out: List[Finding] = []
    in_dunder_hash = False
    for lineno, line in enumerate(text.splitlines(), 1):
        if "def " in line:
            # hash() inside __hash__ feeds per-process dict/set
            # identity only — never consensus state
            in_dunder_hash = "def __hash__" in line
        elif line and not line[0].isspace():
            # any module-level statement ends the __hash__ body
            in_dunder_hash = False
        stripped = _strip_comment(line)  # ignore comments (quote-aware)
        for key, pat, why in BANNED:
            m = pat.search(stripped)
            if not m:
                continue
            if key == "hash" and (in_dunder_hash or
                                  re.match(r"\s*def hash\(", stripped)):
                continue
            out.append(Finding(
                file=rel, line=lineno, rule="nondet", symbol=key,
                message=f"{m.group(0)!r} — {why}"))
    return out


def lint_source(src: str, rel: str) -> List[Finding]:
    """Lint one source text (unit-test hook)."""
    return _lint_lines(src, rel) + _lint_tracing_imports(src, rel)


# Where the scope-drift meta-lint looks: the host-oracle package
# itself. A crypto module that composes other host-oracle modules is
# part of the oracle and must be scoped; importers OUTSIDE the package
# (overlay auth, tx validation) consume verdicts, they don't produce
# them, so they stay out of this rule.
DRIFT_ROOTS = ["stellar_tpu/crypto"]

_ORACLE_IMPORT = re.compile(
    r"^\s*(?:from\s+stellar_tpu\.crypto\s+import\s+(?P<names>.+)|"
    r"(?:from\s+)?(?:import\s+)?stellar_tpu\.crypto\.(?P<dotted>\w+))")


def drift_findings(scope: Optional[List[str]] = None) -> List[Finding]:
    """Scope-drift meta-lint: a module in ``stellar_tpu/crypto`` that
    imports a host-oracle crypto module but is itself absent from
    :data:`HOST_ORACLE_FILES` composes oracle primitives outside the
    nondeterminism fence — new crypto files can no longer silently
    escape the lint. One finding per offending module, at its first
    oracle import."""
    scoped = set(HOST_ORACLE_FILES if scope is None else scope)
    oracle_stems = {f.rsplit("/", 1)[-1][:-3] for f in scoped
                    if f.startswith("stellar_tpu/crypto/")}
    root = repo_root()
    out: List[Finding] = []
    for path in walk_py(DRIFT_ROOTS, root):
        rel = str(path.relative_to(root))
        if rel in scoped:
            continue
        for lineno, line in enumerate(
                path.read_text().splitlines(), 1):
            m = _ORACLE_IMPORT.match(_strip_comment(line))
            if not m:
                continue
            if m.group("dotted"):
                names = [m.group("dotted")]
            else:
                names = [tok.split(" as ")[0].strip() for tok in
                         m.group("names").split(",")]
            hit = sorted(set(names) & oracle_stems)
            if hit:
                out.append(Finding(
                    file=rel, line=lineno, rule="scope-drift",
                    symbol="host-oracle-import",
                    message=f"imports host-oracle module(s) {hit} "
                            "but is not in nondet.HOST_ORACLE_FILES "
                            "— add it (with written allowlist "
                            "arguments for any clock/RNG use) so new "
                            "crypto composition stays inside the "
                            "nondeterminism fence"))
                break
    return out


def run(allowlist: Optional[Allowlist] = None) -> LintReport:
    allowlist = allowlist or ALLOWLIST
    root = repo_root()
    findings: List[Finding] = []
    files = 0
    for path in walk_py(CONSENSUS_DIRS + HOST_ORACLE_FILES, root):
        rel = str(path.relative_to(root))
        files += 1
        text = path.read_text()
        findings.extend(_lint_lines(text, rel))
        findings.extend(_lint_tracing_imports(text, rel))
    findings.extend(drift_findings())
    return finish_report("nondet", files, findings, allowlist)
