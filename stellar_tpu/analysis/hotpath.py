"""Hot-path sync/retrace lint: AST pass over jit-adjacent code.

Two hazard families the verify hot path must stay free of:

* **host-sync hazards** (device files, ``stellar_tpu/ops/``): forcing a
  traced value to the host inside code that runs under ``jit`` —
  ``np.asarray``/``np.array`` on a traced value, ``.item()``,
  ``.tolist()``, ``.block_until_ready()``, ``float()/int()/bool()`` of a
  traced value, and Python control flow (``if``/``while``/``for
  range()``/``assert``) branching on traced data. Any of these either
  fails at trace time or, worse, silently splits the kernel into
  multiple dispatches with a device round-trip between them — the
  exact latency class PR 2's dispatch work is fighting.
* **retrace hazards** (device + dispatch files): building a fresh
  ``jax.jit`` wrapper inside a function body. Each wrapper carries its
  own trace cache, so a per-call wrapper recompiles every call; a
  jitted local closure additionally captures enclosing locals by value
  (shape-carrying or non-hashable captures poison the cache key).

Taint model: function parameters are traced-unknown unless they carry a
non-tensor default (``need_t=True``-style static config, part of the jit
cache key); names assigned from tainted expressions become tainted;
shape-carrying accessors (``.ndim``/``.shape``/``.dtype``/``.size``,
``len()``, ``is None``, ``isinstance``) launder taint — branching on
shapes is trace-time-static and safe.

Findings are filtered through the reviewed allowlist below; every entry
carries a written safety argument (see ``docs/static_analysis.md``).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from stellar_tpu.analysis.lint_base import (
    Allowlist, Finding, LintReport, finish_report, repo_root, walk_py,
)

__all__ = ["run", "lint_source", "SCOPE_DEVICE", "SCOPE_HOST",
           "ALLOWLIST"]

# Files whose function bodies are (or feed) traced device code.
SCOPE_DEVICE = ["stellar_tpu/ops"]
# Host-side dispatch code: retrace rules only. Since ISSUE 7 the
# dispatch loop (and both jit wrapper sites) lives in the generic
# batch engine; the verifier and hasher are thin plugin modules.
SCOPE_HOST = [
    "stellar_tpu/crypto/batch_verifier.py",
    "stellar_tpu/crypto/batch_hasher.py",
    "stellar_tpu/parallel/batch_engine.py",
]

_SYNC_NP_FUNCS = {"asarray", "array"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_SYNC_BUILTINS = {"float", "int", "bool"}
_SHAPEISH_ATTRS = {"ndim", "shape", "dtype", "size"}
_LAUNDER_CALLS = {"len", "isinstance", "hasattr", "getattr", "type"}

# file -> {finding-key: written safety argument}
ALLOWLIST = Allowlist({
    "stellar_tpu/ops/field25519.py": {
        "traced-branch:_pow2k.k":
            "k is a compile-time Python int at every call site (the "
            "literal exponents of the inversion addition chain); the "
            "branch picks unroll-vs-fori_loop at trace time and k is "
            "part of the jit cache key, so no data-dependent control "
            "flow or retrace can occur.",
        "host-sync:from_int.np.array":
            "documented host-side helper: builds a constant limb "
            "vector from a Python int at import/trace time; it is "
            "never called on a traced value (callers pass module "
            "constants or host ints).",
        "host-sync:to_int.np.asarray":
            "documented host-side test helper (docstring says so); "
            "callers are tests and host oracles comparing device "
            "output AFTER an explicit fetch, never traced code.",
    },
    "stellar_tpu/ops/edwards.py": {
        "traced-branch:_unstack_points.n":
            "n is a static Python int (the stack width, always a "
            "literal at call sites) — trace-time unrolling of a "
            "fixed-size tuple, not data-dependent control flow.",
    },
    "stellar_tpu/ops/verify.py": {
        "jit-in-func:verify_kernel_sharded.jax.jit":
            "the wrapper is constructed once per mesh by its callers "
            "(the __graft_entry__ dryrun harness; production dispatch "
            "is per-device sub-chunks of the plain kernel); it never "
            "runs per-dispatch, so there is exactly one trace per "
            "(mesh, bucket) pair.",
    },
    "stellar_tpu/ops/sha256.py": {
        "traced-branch:pack_messages.max_blocks":
            "host-side packing helper (docstring says so): operates "
            "on Python bytes before any device dispatch — max_blocks "
            "is a static Python int (the plugin's block capacity) and "
            "the per-message loop runs over host bytes, never traced "
            "values.",
        "host-sync:digest_words_to_bytes.np.asarray":
            "documented host-side decoder: renders a digest row AFTER "
            "the engine's explicit fetch (callers hold numpy arrays, "
            "never tracers) — the np.asarray is a dtype-cast of host "
            "memory, not a device sync.",
    },
    "stellar_tpu/parallel/batch_engine.py": {
        "jit-in-func:_kernel_for.jax.jit":
            "built once per dispatch shape and memoized in "
            "self._kernels under its lock — the per-call path is a "
            "dict hit, no fresh wrapper and no retrace.",
        "jit-in-func:probe.jax.jit":
            "intentional: each breaker-paced probe must prove the "
            "FULL tunnel including compile+dispatch (a cached wrapper "
            "could vacuously re-close a dispatch-opened breaker); "
            "probes are exponential-backoff-paced, so the recompile "
            "cost is bounded by design.",
    },
})


def _is_shapeish(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and node.attr in _SHAPEISH_ATTRS)


class _FuncLinter:
    """Intraprocedural taint pass over one function body."""

    def __init__(self, fname: str, rel: str, device_file: bool,
                 findings: List[Finding]):
        self.fname = fname
        self.rel = rel
        self.device = device_file
        self.findings = findings
        self.taint: Set[str] = set()

    # --- taint of an expression ---

    def _expr_tainted(self, node: ast.AST) -> bool:
        if node is None or isinstance(node, (ast.Constant,)):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.taint
        if _is_shapeish(node):
            return False  # shapes are static under trace
        if isinstance(node, ast.Attribute):
            return self._expr_tainted(node.value)
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in _LAUNDER_CALLS:
                return False
            parts = [fn] + list(node.args) + \
                [kw.value for kw in node.keywords]
            return any(self._expr_tainted(p) for p in parts)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False  # `x is None` guards are structural
            return any(self._expr_tainted(c)
                       for c in [node.left] + list(node.comparators))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.comprehension)):
                if self._expr_tainted(child):
                    return True
        return False

    # --- rules ---

    def _emit(self, node: ast.AST, rule: str, symbol: str, msg: str):
        self.findings.append(Finding(
            file=self.rel, line=getattr(node, "lineno", 0), rule=rule,
            symbol=symbol, message=msg))

    def _check_sync_call(self, node: ast.Call):
        fn = node.func
        args_tainted = any(self._expr_tainted(a) for a in node.args)
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "np" \
                and fn.attr in _SYNC_NP_FUNCS and args_tainted:
            self._emit(node, "host-sync",
                       f"{self.fname}.np.{fn.attr}",
                       f"np.{fn.attr} on a traced value forces a "
                       "host sync / concretization inside jitted code")
        elif isinstance(fn, ast.Attribute) and \
                fn.attr in _SYNC_METHODS and \
                self._expr_tainted(fn.value):
            self._emit(node, "host-sync",
                       f"{self.fname}.{fn.attr}",
                       f".{fn.attr}() on a traced value blocks on "
                       "device transfer")
        elif isinstance(fn, ast.Name) and fn.id in _SYNC_BUILTINS and \
                args_tainted:
            self._emit(node, "host-sync",
                       f"{self.fname}.{fn.id}",
                       f"{fn.id}() of a traced value concretizes at "
                       "trace time (or fails)")

    @staticmethod
    def _is_jit_expr(node: ast.AST) -> bool:
        """jax.jit / bare `jit` (from jax import jit) /
        functools.partial(jax.jit, ...) — anything that builds a fresh
        jit wrapper when evaluated."""
        if isinstance(node, ast.Attribute) and node.attr == "jit" and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "jax":
            return True
        if isinstance(node, ast.Name) and node.id == "jit":
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            is_partial = (
                (isinstance(fn, ast.Attribute) and fn.attr == "partial")
                or (isinstance(fn, ast.Name) and fn.id == "partial"))
            if is_partial:
                return any(_FuncLinter._is_jit_expr(a)
                           for a in node.args)
        return False

    def _emit_jit(self, node: ast.AST, symbol: str, captures: str = ""):
        self._emit(node, "jit-in-func", f"{self.fname}.{symbol}",
                   "jax.jit wrapper built inside a function body: a "
                   "fresh wrapper per call means a fresh trace cache "
                   "per call (recompile every time)" + captures)

    def _check_jit_call(self, node: ast.Call):
        if not self._is_jit_expr(node.func):
            return
        captures = ""
        if node.args and isinstance(node.args[0], ast.Lambda):
            captures = (" (jitted lambda: closure captures become "
                        "part of the trace, shape-carrying or "
                        "non-hashable captures poison the cache)")
        self._emit_jit(node, "jax.jit", captures)

    def _check_jit_decorators(self, fnode) -> None:
        """A nested def decorated with @jax.jit / @jit / @partial(jit)
        builds a fresh wrapper every time the enclosing function runs —
        the decorator spelling of the same retrace hazard."""
        for dec in fnode.decorator_list:
            if self._is_jit_expr(dec):
                self._emit_jit(
                    dec, f"{fnode.name}.jax.jit",
                    " (decorated nested def: its closure captures "
                    "become part of the trace)")

    def run(self, fnode: ast.FunctionDef):
        # parameters without a static (non-tensor literal) default are
        # traced-unknown
        args = fnode.args
        all_args = (args.posonlyargs + args.args + args.kwonlyargs)
        defaults = ([None] * (len(args.posonlyargs) + len(args.args)
                              - len(args.defaults))
                    + list(args.defaults) + list(args.kw_defaults))
        for a, d in zip(all_args, defaults):
            if a.arg in ("self", "cls"):
                continue
            if d is not None and isinstance(d, ast.Constant):
                continue  # static config default: part of the cache key
            if d is not None and isinstance(d, ast.Tuple) and \
                    all(isinstance(e, ast.Constant) for e in d.elts):
                continue
            self.taint.add(a.arg)
        if args.vararg:
            self.taint.add(args.vararg.arg)
        if args.kwarg:
            self.taint.add(args.kwarg.arg)

        # two forward passes so loop-carried taint converges
        for _ in range(2):
            for node in self._walk_own(fnode):
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    value = getattr(node, "value", None)
                    if value is None or not self._expr_tainted(value):
                        continue
                    targets = node.targets if isinstance(
                        node, ast.Assign) else [node.target]
                    for t in targets:
                        self._taint_target(t)
                elif isinstance(node, ast.For):
                    if self._expr_tainted(node.iter):
                        self._taint_target(node.target)

        for node in self._walk_own(fnode):
            if isinstance(node, ast.Call):
                if self.device:
                    self._check_sync_call(node)
                self._check_jit_call(node)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                # nested def: its body is the nested linter's scope,
                # but ITS decorators evaluate in THIS scope, per call
                self._check_jit_decorators(node)
            elif self.device and isinstance(node,
                                            (ast.If, ast.While)):
                if self._expr_tainted(node.test):
                    sym = self._cond_symbol(node.test)
                    self._emit(
                        node, "traced-branch", f"{self.fname}.{sym}",
                        "Python branch on a traced value inside "
                        "device code: fails at trace time or forces "
                        "a concretizing sync")
            elif self.device and isinstance(node, ast.Assert):
                if self._expr_tainted(node.test):
                    sym = self._cond_symbol(node.test)
                    self._emit(
                        node, "traced-branch", f"{self.fname}.{sym}",
                        "assert on a traced value inside device code")
            elif self.device and isinstance(node, ast.For):
                if self._range_tainted(node.iter):
                    sym = self._cond_symbol(node.iter)
                    self._emit(
                        node, "traced-branch", f"{self.fname}.{sym}",
                        "Python loop with a data-dependent trip count "
                        "(range over a traced value) inside device "
                        "code")
            elif self.device and isinstance(
                    node, (ast.ListComp, ast.SetComp, ast.DictComp,
                           ast.GeneratorExp)):
                for comp in node.generators:
                    if self._range_tainted(comp.iter):
                        sym = self._cond_symbol(comp.iter)
                        self._emit(
                            node, "traced-branch",
                            f"{self.fname}.{sym}",
                            "comprehension with a data-dependent trip "
                            "count (range over a traced value) inside "
                            "device code")

    def _range_tainted(self, it: ast.AST) -> bool:
        """True for ``range(<tainted>)``-shaped iterators: the trip
        count itself is data-dependent. Iterating a tuple/zip of traced
        arrays is static-width unrolling and is NOT flagged."""
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id in ("range", "reversed"):
            return any(self._expr_tainted(a) for a in it.args)
        return False

    @staticmethod
    def _walk_own(fnode: ast.FunctionDef):
        """Walk a function body in SOURCE ORDER without descending into
        nested function definitions (each nested def gets its own
        linter scope). Source order matters: the taint passes are
        forward dataflow — a reversed walk would only propagate taint
        one assignment link per pass."""
        stack = list(reversed(list(ast.iter_child_nodes(fnode))))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue
            stack.extend(reversed(list(ast.iter_child_nodes(node))))

    def _taint_target(self, t: ast.AST):
        """Taint assignment-target names: a subscripted target taints
        its base container, never the index expression's names."""
        if isinstance(t, ast.Name):
            self.taint.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._taint_target(e)
        elif isinstance(t, (ast.Subscript, ast.Attribute, ast.Starred)):
            base = t.value if not isinstance(t, ast.Starred) else t.value
            if isinstance(t, ast.Starred):
                self._taint_target(t.value)
            else:
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if isinstance(base, ast.Name):
                    self.taint.add(base.id)

    def _cond_symbol(self, node: ast.AST) -> str:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id in self.taint:
                return n.id
        return "<expr>"


def _lint_tree(tree: ast.Module, rel: str, device_file: bool,
               findings: List[Finding]):
    # lint every function (including nested defs, each with its own
    # taint scope; nested functions inherit nothing — conservative for
    # closures, which is fine: closure reads of traced locals surface
    # at their own call sites)
    def visit(node, prefix=""):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                linter = _FuncLinter(child.name, rel, device_file,
                                     findings)
                linter.run(child)
                visit(child, prefix + child.name + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, prefix + child.name + ".")
            else:
                visit(child, prefix)
    visit(tree)


def lint_source(src: str, rel: str,
                device_file: bool = True) -> List[Finding]:
    """Lint one source text (unit-test hook)."""
    findings: List[Finding] = []
    _lint_tree(ast.parse(src), rel, device_file, findings)
    return findings


def run(allowlist: Optional[Allowlist] = None) -> LintReport:
    allowlist = allowlist or ALLOWLIST
    root = repo_root()
    findings: List[Finding] = []
    files = 0
    for paths, device in ((SCOPE_DEVICE, True), (SCOPE_HOST, False)):
        for path in walk_py(paths, root):
            rel = str(path.relative_to(root))
            files += 1
            _lint_tree(ast.parse(path.read_text()), rel, device,
                       findings)
    return finish_report("hotpath", files, findings, allowlist)
