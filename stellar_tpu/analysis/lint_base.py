"""Shared lint framework: findings, reviewed allowlists, file walking.

Every AST lint in this package (hotpath, locks, nondet) produces
:class:`Finding` objects and filters them through a reviewed
:class:`Allowlist` whose entries REQUIRE a written safety argument — an
allowlist entry without a reason is itself an error. The framework also
reports *stale* allowlist entries (entries matching nothing), so the
allowlist can only shrink to fit the code, never silently outgrow it.

Finding keys are ``<rule>:<symbol>`` strings, stable across line-number
churn; the allowlist maps ``repo-relative-path -> {key: reason}``.
"""

from __future__ import annotations

import dataclasses
import pathlib
from typing import Dict, Iterable, List, Sequence, Tuple

__all__ = ["Finding", "Allowlist", "LintReport", "repo_root", "walk_py"]


def repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parent.parent.parent


@dataclasses.dataclass
class Finding:
    """One lint hit: ``key`` is ``<rule>:<symbol>`` (allowlist-stable),
    ``message`` explains the hazard, ``why`` the rule's rationale."""
    file: str          # repo-relative path
    line: int
    rule: str
    symbol: str        # function/attr the finding anchors to
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.symbol}"

    def describe(self) -> str:
        return f"{self.file}:{self.line}: [{self.key}] {self.message}"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["key"] = self.key
        return d


class Allowlist:
    """Reviewed exceptions: ``{file: {finding-key: safety argument}}``.

    Matching is exact on (file, key). Every entry must carry a
    non-empty written reason; :meth:`stale` lists entries that matched
    no finding (dead entries must be deleted, not accumulated)."""

    def __init__(self, entries: Dict[str, Dict[str, str]]):
        for path, keys in entries.items():
            for key, reason in keys.items():
                if not isinstance(reason, str) or len(reason.strip()) < 10:
                    raise ValueError(
                        f"allowlist entry {path}:{key} needs a written "
                        f"safety argument (got {reason!r})")
        self._entries = entries
        self._hits: set = set()

    def match(self, finding: Finding) -> str:
        """Return the safety argument if allowlisted, else ''."""
        reason = self._entries.get(finding.file, {}).get(finding.key, "")
        if reason:
            self._hits.add((finding.file, finding.key))
        return reason

    def stale(self) -> List[str]:
        out = []
        for path, keys in self._entries.items():
            for key in keys:
                if (path, key) not in self._hits:
                    out.append(f"{path}:{key}")
        return sorted(out)


@dataclasses.dataclass
class LintReport:
    """One lint pass's result: open findings fail the gate; allowlisted
    ones are carried (with their safety argument) for visibility."""
    name: str
    files_scanned: int
    findings: List[Finding]
    allowlisted: List[Tuple[Finding, str]]
    stale_allowlist: List[str]

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_allowlist

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "findings": [f.to_dict() for f in self.findings],
            "allowlisted": [
                {**f.to_dict(), "reason": reason}
                for f, reason in self.allowlisted],
            "stale_allowlist": self.stale_allowlist,
        }

    def describe(self) -> str:
        lines = [f.describe() for f in self.findings]
        lines += [f"stale allowlist entry (delete it): {e}"
                  for e in self.stale_allowlist]
        return "\n".join(lines)


def finish_report(name: str, files_scanned: int,
                  raw: Iterable[Finding],
                  allowlist: Allowlist) -> LintReport:
    """Split raw findings into open vs allowlisted and close the report."""
    findings: List[Finding] = []
    allowed: List[Tuple[Finding, str]] = []
    for f in raw:
        reason = allowlist.match(f)
        if reason:
            allowed.append((f, reason))
        else:
            findings.append(f)
    return LintReport(name=name, files_scanned=files_scanned,
                      findings=findings, allowlisted=allowed,
                      stale_allowlist=allowlist.stale())


def walk_py(paths: Sequence[str],
            root: pathlib.Path = None) -> List[pathlib.Path]:
    """Expand repo-relative files/dirs to sorted .py paths."""
    root = root or repo_root()
    out: List[pathlib.Path] = []
    for p in paths:
        full = root / p
        if full.is_dir():
            out.extend(sorted(full.rglob("*.py")))
        elif full.exists():
            out.append(full)
    return out
