"""Lock-discipline lint: shared mutable state must mutate under its lock.

PR 2 introduced real threading around the verify boundary — resolve
watchdogs, probe threads, the trickle-batch leader, breaker-paced
callbacks — guarded only by convention. This AST pass makes the
convention checkable over the threaded modules:

* **instance state** (``unlocked-attr``): in a class that owns a lock
  (an ``__init__`` attribute assigned from ``threading.Lock/RLock/
  Condition``), every mutation of ``self.<attr>`` outside ``__init__``
  — assignment, augmented assignment, subscript store, or a mutating
  container-method call — must sit lexically inside ``with
  self.<lock>:``.
* **module globals** (``unlocked-global``): a function that declares
  ``global X`` and assigns ``X`` in a module that owns module-level
  locks must do so inside ``with <lock>:``.

Convention the lint encodes rather than flags: functions/methods whose
name ends in ``_locked`` are called with the lock already held (the
repo-wide naming contract, e.g. ``_account_probe_locked``) and are
exempt; ``__init__``/``__new__`` run before the object is shared and
are exempt. Lexical containment is the whole analysis — a lock taken in
a caller does not count, which is exactly why the ``_locked`` suffix
contract exists.

Limitation (documented in ``docs/static_analysis.md``): a class with NO
lock attribute is invisible to this pass — shared lock-free classes
must first grow a lock (as ``utils/metrics.py`` did in this PR) to come
under enforcement.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from stellar_tpu.analysis.lint_base import (
    Allowlist, Finding, LintReport, finish_report, repo_root, walk_py,
)

__all__ = ["run", "lint_source", "drift_findings", "SCOPE",
           "DRIFT_ROOTS", "ALLOWLIST"]

# The threaded modules: verify dispatch, resilience primitives (incl.
# the watchdog pool), the per-device health registry, the metrics
# registry they all mark into (reservoir replacement is an RMW), the
# tracing layer's flight-recorder ring (marked from resolver, pool
# worker and breaker-callback threads), and the device-watch daemon.
SCOPE = [
    "stellar_tpu/crypto/batch_verifier.py",
    "stellar_tpu/crypto/batch_hasher.py",
    "stellar_tpu/crypto/verify_service.py",
    # the tenant QoS layer (ISSUE 14): policy table + per-tenant SLO
    # windows mutate from caller and dispatcher threads under this
    # module's own locks (the lane queues are service-internal state,
    # touched only with the service cv held — the _locked convention)
    "stellar_tpu/crypto/tenant.py",
    # the closed-loop controller (ISSUE 15): trajectory log + knob
    # state mutate from the dispatcher thread while admin routes read
    # snapshots — everything under the controller's own lock; the
    # service applies the resulting knob values under its cv
    "stellar_tpu/crypto/controller.py",
    # the fleet router (ISSUE 17): replica states, conservation
    # counters and submission ledgers mutate from every submitting
    # thread while admin routes read snapshots and the divergence
    # detector re-reads replica logs — everything under the router's
    # own lock (the _locked convention); the shared-engine adapter
    # serializes replica dispatchers on one engine
    "stellar_tpu/crypto/fleet.py",
    # the wire ingress (ISSUE 19): connection registry + the
    # wire-extended conservation counters mutate from accept, reader,
    # responder and snapshot threads under the server's one cv; the
    # design contract the lockorder prover enforces is that NO lock is
    # ever held across a socket op (recv/accept/sendall)
    "stellar_tpu/crypto/ingress.py",
    # the frame codec is lock-free and thread-free by design; scoped
    # so the prover's graph covers the whole wire path and any future
    # lock sneaking in is caught, not argued
    "stellar_tpu/utils/wire.py",
    # the unified system journal (ISSUE 20) is likewise lock-free by
    # design — it reads other components' logs through THEIR locked
    # accessors and never holds anything itself; scoped so a lock
    # (and with it a new ordering edge against the component locks it
    # reads under) can never sneak in unseen
    "stellar_tpu/utils/journal.py",
    # the reusable receive-buffer pool (ISSUE 19): free list + lease
    # refcounts mutate from reader and responder threads under the
    # pool's one lock
    "stellar_tpu/parallel/hostbuf.py",
    "stellar_tpu/parallel/batch_engine.py",
    "stellar_tpu/parallel/device_health.py",
    # the device-resident constant cache (ISSUE 12): its LRU mutates
    # from every dispatching thread (trickle leaders, service
    # dispatcher, chaos tests) through the engine's placement path
    "stellar_tpu/parallel/residency.py",
    # the per-pubkey signer-table cache (ISSUE 16): its LRU mutates
    # from every submitting thread at partition time and from the
    # engine's audit-conviction eviction hook
    "stellar_tpu/parallel/signer_tables.py",
    "stellar_tpu/utils/resilience.py",
    "stellar_tpu/utils/metrics.py",
    # the background worker pool: pool pointer + mode flag mutate from
    # app setup, determinism tests and shutdown while crank threads
    # submit (ISSUE 18 brought it under enforcement and fixed a
    # shutdown-under-lock hold-and-block)
    "stellar_tpu/utils/workers.py",
    # the fault-injection registry: chaos tests arm/disarm points
    # while every dispatch-path thread consults them
    "stellar_tpu/utils/faults.py",
    # the verify-cache + backend selector: seeded from the crank,
    # read and refilled from every verifying thread
    "stellar_tpu/crypto/keys.py",
    # the four native-library loaders share one idiom: a module lock
    # serializing a one-shot g++ compile-and-dlopen (the lockorder
    # pass carries the written hold-and-block safety argument)
    "stellar_tpu/utils/native.py",
    "stellar_tpu/crypto/native_prep.py",
    "stellar_tpu/crypto/native_verify.py",
    "stellar_tpu/soroban/native_wasm.py",
    # the XDR pack-tree compiler: its RLock serializes the one-time
    # recursive compile of composite pack trees; the registry and
    # keepalive caches refill from any encoding thread
    "stellar_tpu/xdr/runtime.py",
    "stellar_tpu/utils/tracing.py",
    "stellar_tpu/utils/transfer_ledger.py",
    # the pipeline-bubble profiler's tokens/ring mutate from
    # submitter + resolver + service-dispatcher threads, and the
    # time-series ring (inside metrics.py, already scoped) is sampled
    # concurrently with resolving engines (ISSUE 10)
    "stellar_tpu/utils/timeline.py",
    "tools/device_watch.py",
]

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_MUTATORS = {"append", "extend", "insert", "add", "update", "pop",
             "popleft", "popitem", "clear", "remove", "discard",
             "setdefault", "appendleft", "sort", "reverse"}


def _expr_calls(node: ast.AST):
    """Every Call in the EXPRESSION children of one STATEMENT — never
    descending into nested sub-statements (an `if` body's statements
    are visited separately) and yielding nothing for non-statement
    nodes, so each call is seen exactly once."""
    if not isinstance(node, ast.stmt):
        return
    for sub in ast.iter_child_nodes(node):
        if isinstance(sub, ast.expr):
            for n in ast.walk(sub):
                if isinstance(n, ast.Call):
                    yield n

# Where the scope-drift meta-lint looks for lock constructors that
# escaped SCOPE: the whole shipped package. tools/ scripts are opted in
# by listing them in SCOPE explicitly (device_watch.py is).
DRIFT_ROOTS = ["stellar_tpu"]

ALLOWLIST = Allowlist({
    "stellar_tpu/main/command_handler.py": {
        "scope-drift:lock-ctor":
            "QueryServer's BoundedSemaphore is a concurrency "
            "throttle bounding in-flight ledger-entry queries "
            "(reference QUERY_THREAD_POOL_SIZE), not a guard over "
            "shared mutable state — there is no attribute the "
            "mutation lint could bind it to, and the handler tier's "
            "shared state lives behind module locks already in SCOPE.",
    },
    "stellar_tpu/utils/timer.py": {
        "scope-drift:lock-ctor":
            "VirtualClock is single-threaded by crank discipline: "
            "every mutation happens on the crank thread, and its one "
            "lock guards only the cross-thread post_to_main queue "
            "(posts under lock, crank drains under lock). The "
            "mutation lint's every-attr-under-lock contract does not "
            "describe this design, so the module stays out of SCOPE "
            "with this written argument instead.",
    },
    "stellar_tpu/parallel/batch_engine.py": {
        "unlocked-global:configure_dispatch.DEADLINE_MS":
            "single atomic store of an immutable float (no "
            "read-modify-write): under the GIL a concurrent reader "
            "sees either the old or the new deadline, both valid — "
            "and the knob is pushed once at Application setup, before "
            "concurrent dispatch exists.",
        "unlocked-global:configure_dispatch.DISPATCH_RETRIES":
            "single atomic store of an immutable int (no "
            "read-modify-write): same argument as DEADLINE_MS — "
            "config push at startup, torn reads impossible under the "
            "GIL.",
        "unlocked-global:configure_dispatch.AUDIT_RATE":
            "single atomic store of an immutable float (no "
            "read-modify-write): same argument as DEADLINE_MS — "
            "config push at startup, torn reads impossible under the "
            "GIL; a racing resolve sees either the old or the new "
            "rate, both of which sample deterministically.",
        "unlocked-global:configure_dispatch.DONATE_BUFFERS":
            "single atomic store of an immutable str (no "
            "read-modify-write): same argument as DEADLINE_MS — "
            "config push at startup; a racing dispatch reads either "
            "the old or the new policy, and both produce "
            "bit-identical results (donation changes buffer "
            "lifetimes, never rows).",
    },
})


def _is_lock_ctor(node: ast.AST) -> bool:
    """threading.Lock() / threading.RLock() / Condition() etc."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_CTORS:
        return True
    if isinstance(fn, ast.Name) and fn.id in _LOCK_CTORS:
        return True
    return False


class _ClassLinter:
    """Check one class body for unlocked self-attribute mutations."""

    def __init__(self, cnode: ast.ClassDef, rel: str,
                 findings: List[Finding]):
        self.cnode = cnode
        self.rel = rel
        self.findings = findings
        self.locks: Set[str] = set()
        self._collect_locks()

    def _collect_locks(self):
        for node in ast.walk(self.cnode):
            if isinstance(node, ast.Assign) and \
                    _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        self.locks.add(t.attr)

    def _is_with_lock(self, node: ast.With) -> bool:
        for item in node.items:
            e = item.context_expr
            if isinstance(e, ast.Attribute) and \
                    isinstance(e.value, ast.Name) and \
                    e.value.id == "self" and e.attr in self.locks:
                return True
        return False

    def run(self):
        if not self.locks:
            return  # lock-free class: outside this pass's contract
        for node in self.cnode.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self._check_func(node, top_name=node.name)

    def _check_func(self, fnode, top_name: str):
        if top_name in ("__init__", "__new__") or \
                top_name.endswith("_locked"):
            return
        self._scan(fnode, guarded=False, func=top_name)

    def _scan(self, node: ast.AST, guarded: bool, func: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                g = guarded or self._is_with_lock(child)
                self._scan(child, g, func)
                continue
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                if child.name.endswith("_locked"):
                    continue
                # nested defs (resolver closures) still touch self
                self._scan(child, False, f"{func}.{child.name}")
                continue
            if not guarded:
                self._check_stmt(child, func)
            self._scan(child, guarded, func)

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        """The self-rooted attribute a target/receiver mutates:
        ``self.a``, ``self.a[...]``, ``self.a.b[...].c`` all resolve to
        ``a`` — mutating a nested object still mutates state reached
        through self."""
        first_attr = None
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            if isinstance(node, ast.Attribute):
                first_attr = node.attr
            node = node.value
        if isinstance(node, ast.Name) and node.id == "self":
            return first_attr
        return None

    def _iter_targets(self, t: ast.AST):
        """Flatten tuple/list/starred unpacking targets."""
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from self._iter_targets(e)
        elif isinstance(t, ast.Starred):
            yield from self._iter_targets(t.value)
        else:
            yield t

    def _emit(self, node: ast.AST, func: str, attr: str, what: str):
        self.findings.append(Finding(
            file=self.rel, line=node.lineno, rule="unlocked-attr",
            symbol=f"{self.cnode.name}.{func}.{attr}",
            message=f"{what} outside `with self.<lock>` in a "
                    f"lock-owning class ({sorted(self.locks)})"))

    def _check_stmt(self, node: ast.AST, func: str):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for raw in targets:
                for t in self._iter_targets(raw):
                    attr = self._self_attr(t)
                    if attr and attr not in self.locks:
                        self._emit(node, func, attr,
                                   f"self.{attr} mutated")
        # mutator calls count wherever they appear in THIS statement's
        # expressions — bare statement, assigned result, or inside an
        # if/while/for/assert/raise head (sub-statements are handled by
        # _scan's own recursion, so only expression children are walked
        # here to avoid double counting)
        for call in _expr_calls(node):
            fn = call.func
            if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
                attr = self._self_attr(fn.value)
                if attr and attr not in self.locks:
                    self._emit(node, func, attr,
                               f"self.{attr}.{fn.attr}()")


_MUTABLE_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                  "OrderedDict", "Counter"}


class _ModuleLinter:
    """Check module-global mutations against module-level locks.

    Two mutation spellings, because only the first needs ``global``:

    * rebinding a declared global (``global X; X = ...``);
    * in-place mutation of a module-level mutable (``_CACHE[k] = v``,
      ``_EVENTS.append(e)``) — the common shared-dict/list idiom, which
      never declares ``global`` at all.
    """

    def __init__(self, tree: ast.Module, rel: str,
                 findings: List[Finding]):
        self.tree = tree
        self.rel = rel
        self.findings = findings
        self.locks: Set[str] = set()
        self.mutables: Set[str] = set()
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.locks.add(t.id)
            elif self._is_mutable_literal(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.mutables.add(t.id)

    @staticmethod
    def _is_mutable_literal(node: ast.AST) -> bool:
        if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else \
                fn.attr if isinstance(fn, ast.Attribute) else ""
            return name in _MUTABLE_CTORS
        return False

    def _is_with_lock(self, node: ast.With) -> bool:
        for item in node.items:
            e = item.context_expr
            if isinstance(e, ast.Name) and e.id in self.locks:
                return True
        return False

    def run(self):
        if not self.locks:
            return  # module owns no locks: single-threaded by design
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                if node.name.endswith("_locked"):
                    continue
                declared: Set[str] = set()
                local_shadows: Set[str] = set()
                for n in ast.walk(node):
                    if isinstance(n, ast.Global):
                        declared.update(n.names)
                    elif isinstance(n, ast.Assign):
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                local_shadows.add(t.id)
                watched = declared | (self.mutables -
                                      (local_shadows - declared))
                if not watched:
                    continue
                self._scan(node, False, node.name, declared, watched)

    def _emit(self, node: ast.AST, func: str, name: str, what: str):
        self.findings.append(Finding(
            file=self.rel, line=node.lineno, rule="unlocked-global",
            symbol=f"{func}.{name}",
            message=f"{what} outside `with <module lock>` "
                    f"({sorted(self.locks)})"))

    def _scan(self, node: ast.AST, guarded: bool, func: str,
              declared: Set[str], watched: Set[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                self._scan(child, guarded or self._is_with_lock(child),
                           func, declared, watched)
                continue
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                continue  # nested defs get their own scan
            if not guarded and isinstance(
                    child, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = child.targets if isinstance(
                    child, ast.Assign) else [child.target]
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in declared:
                        self._emit(child, func, t.id,
                                   f"global {t.id} assigned")
                    elif isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id in watched:
                        self._emit(child, func, t.value.id,
                                   f"{t.value.id}[...] stored")
            if not guarded:
                for call in _expr_calls(child):
                    fn = call.func
                    if isinstance(fn, ast.Attribute) and \
                            fn.attr in _MUTATORS and \
                            isinstance(fn.value, ast.Name) and \
                            fn.value.id in watched:
                        self._emit(child, func, fn.value.id,
                                   f"{fn.value.id}.{fn.attr}()")
            self._scan(child, guarded, func, declared, watched)


def lint_source(src: str, rel: str) -> List[Finding]:
    """Lint one source text (unit-test / mutation-test hook)."""
    findings: List[Finding] = []
    tree = ast.parse(src)
    _ModuleLinter(tree, rel, findings).run()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            _ClassLinter(node, rel, findings).run()
    return findings


def drift_findings(scope: Optional[List[str]] = None,
                   roots: Optional[List[str]] = None) -> List[Finding]:
    """Scope-drift meta-lint: a module under ``stellar_tpu/`` that
    constructs a ``threading`` lock but is absent from :data:`SCOPE`
    escapes both the mutation lint and the lock-order prover — new
    threaded files can no longer do that silently. One finding per
    offending module, at its first lock constructor."""
    scoped = set(SCOPE if scope is None else scope)
    root = repo_root()
    out: List[Finding] = []
    for path in walk_py(roots or DRIFT_ROOTS, root):
        rel = str(path.relative_to(root))
        if rel in scoped:
            continue
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:  # pragma: no cover - tree is parseable
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and \
                    _is_lock_ctor(node.value):
                out.append(Finding(
                    file=rel, line=node.lineno, rule="scope-drift",
                    symbol="lock-ctor",
                    message="module constructs a threading lock but "
                            "is not in locks.SCOPE — add it (mutation "
                            "lint + lock-order prover) or write a "
                            "safety argument in locks.ALLOWLIST"))
                break
    return out


def run(allowlist: Optional[Allowlist] = None) -> LintReport:
    allowlist = allowlist or ALLOWLIST
    root = repo_root()
    findings: List[Finding] = []
    files = 0
    for path in walk_py(SCOPE, root):
        rel = str(path.relative_to(root))
        files += 1
        findings.extend(lint_source(path.read_text(), rel))
    findings.extend(drift_findings())
    return finish_report("locks", files, findings, allowlist)
