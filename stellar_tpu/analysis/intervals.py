"""Interval-domain abstract interpretation over jaxprs.

The verify kernel's correctness rests on a range claim: with 13-bit limbs
bounded by ``LOOSE_MAX``, every schoolbook-product coefficient stays below
2^31 (``ops/field25519.py``). That claim was informal — a comment plus an
empirical spot check — and every kernel rework (signed windows in PR 1,
the batched-affine tables + Montgomery inversion chain + strength-reduced
carry fold in PR 13) re-perturbs exactly the limb magnitudes it covers.
This module makes it machine-checked, in the spirit of "Efficient
Verification of Optimized Code: Correct High-speed X25519" (PAPERS.md):
abstract-interpret the traced jaxpr with per-element ``[lo, hi]``
intervals in exact integer arithmetic and flag every equation whose
output interval escapes its dtype.

Design notes:

* **Exact integer intervals, saturated at 2^61.** All bounds are int64;
  products/shifts/sums are float64-guarded and saturate at ``SAT`` rather
  than wrap, so an already-overflowed bound can never launder itself back
  into range through int64 wraparound. Saturation only ever *keeps* a
  bound out of dtype range, and every equation is checked at its own
  site, so a violation is reported where it happens even though
  downstream bounds are then clamped (wrap semantics: a wrapped int32 can
  be anything in int32 range — that IS the clamp).
* **Batch-collapsed storage.** Verify batches are data-parallel: bounds
  are uniform along the batch axis, so abstract arrays store size-1 dims
  wherever the interval is uniform (numpy broadcasting does the rest).
  Analysis cost is near batch-size-independent — the 16384 bucket costs
  what the 128 bucket costs — while the limb axis keeps full per-limb
  resolution (the whole point: limb 0 carries the 608x fold, limb 19 the
  top digit).
* **Loops.** ``scan`` (every ``fori_loop`` in the kernel lowers to it)
  is UNROLLED EXACTLY when its static trip count is at most
  ``max_unroll`` (256; every kernel loop is <= 100) — per-iteration
  bounds, no over-approximation, made cheap by the incremental body
  evaluator (see :class:`_IncrementalBody`). Longer scans fall back to
  a join fixed point with threshold widening on the carry, whose final
  recorded pass checks every body equation under the (dtype-clamped)
  invariant — sound but possibly imprecise, and loud if the
  imprecision reaches a violation. A join fixed point can never close
  over an incrementing loop counter (``f([0,n]) = [1,n+1]``), which is
  exactly why bounded unrolling is the primary strategy.
* **Loud by construction.** Any primitive, padding mode, or scatter shape
  outside the verified subset raises :class:`Unsupported` — the prover
  refuses to claim a proof over code it did not model.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "AbsVal", "Violation", "Unsupported", "IntervalInterpreter",
    "interval_for_dtype", "SAT",
]

# Saturation bound for abstract values: far above any dtype the kernel
# uses, far below int64 wraparound even after one addition.
SAT = np.int64(1) << np.int64(61)


class Unsupported(Exception):
    """The jaxpr uses a primitive/feature outside the verified subset."""


@dataclasses.dataclass
class Violation:
    """One equation whose output interval escapes its dtype."""
    path: str          # nesting path, e.g. "dsm/pjit:mul/scan@41"
    eqn_index: int     # equation index within that (sub)jaxpr
    primitive: str
    dtype: str
    lo: int
    hi: int
    dtype_min: int
    dtype_max: int
    where: str         # user source location from jax source_info

    def describe(self) -> str:
        return (f"{self.path}[{self.eqn_index}] {self.primitive} -> "
                f"[{self.lo}, {self.hi}] escapes {self.dtype} "
                f"[{self.dtype_min}, {self.dtype_max}] at {self.where}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def interval_for_dtype(dtype) -> Tuple[int, int]:
    dtype = np.dtype(dtype)
    if dtype == np.bool_:
        return 0, 1
    if dtype.kind in "iu":
        info = np.iinfo(dtype)
        return int(info.min), int(info.max)
    raise Unsupported(f"non-integer dtype {dtype} in checked jaxpr")


def _clamp(a: np.ndarray) -> np.ndarray:
    return np.clip(a, -SAT, SAT)


def _safe_mul(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Exact int64 product, saturated where float64 says it would leave
    [-SAT, SAT] (float magnitude error is negligible at the 2^61 scale)."""
    fx = x.astype(np.float64) * y.astype(np.float64)
    big = np.abs(fx) >= float(SAT)
    with np.errstate(over="ignore"):
        exact = x * y
    if not big.any():
        return exact
    return np.where(big, np.where(fx > 0, SAT, -SAT), exact)


def _safe_sum(a: np.ndarray, axis: int) -> np.ndarray:
    f = a.astype(np.float64).sum(axis=axis)
    big = np.abs(f) >= float(SAT)
    exact = a.sum(axis=axis)
    if not big.any():
        return exact
    return np.where(big, np.where(f > 0, SAT, -SAT), exact)


class AbsVal:
    """Interval abstraction of one traced array.

    ``lo``/``hi`` are int64 arrays broadcast-compatible with the concrete
    ``shape``: any dim may be stored with size 1 when the bound is
    uniform along it (the batch axis always is).

    ``excl`` is a relational refinement: the set of axes along which AT
    MOST ONE element is nonzero (for every fixed index of the other
    axes). It is born at ``eq(pairwise-distinct constant, uniform)`` —
    the one-hot idiom — survives convert/broadcast/reshape/multiply, and
    is consumed by ``reduce_sum``, which then takes the union bound
    instead of the sum. Without it, the kernel's 8-entry window selects
    would inflate 8x and falsely 'overflow' the downstream multiplies.

    ``vuni`` is the companion refinement that makes ``excl``'s birth
    sound: the set of axes along which the runtime VALUE is provably
    the same at every position. Only a broadcast (size-1 -> N), a
    size-1 concrete extent, or a uniform constant establishes it —
    uniform *bounds* (stored-size-1) never do, because a traced input
    can vary within uniform bounds."""

    __slots__ = ("lo", "hi", "shape", "dtype", "excl", "vuni")

    def __init__(self, lo, hi, shape, dtype, excl=frozenset(),
                 vuni=frozenset()):
        self.lo = np.asarray(lo, dtype=np.int64)
        self.hi = np.asarray(hi, dtype=np.int64)
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self.excl = frozenset(excl)
        self.vuni = frozenset(vuni)
        if self.lo.shape != self.hi.shape:
            raise AssertionError("lo/hi shape mismatch")
        if self.lo.ndim != len(self.shape):
            # scalars may arrive rank-0 against a rank-0 aval only
            raise AssertionError(
                f"stored rank {self.lo.ndim} vs concrete {self.shape}")
        for stored, concrete in zip(self.lo.shape, self.shape):
            if stored not in (1, concrete):
                raise AssertionError(
                    f"stored {self.lo.shape} vs concrete {self.shape}")

    # ---------------- constructors ----------------

    @classmethod
    def from_concrete(cls, value) -> "AbsVal":
        a = np.asarray(value)
        if a.dtype.kind in "biu":
            ai = a.astype(np.int64)
        else:
            raise Unsupported(f"non-integer constant dtype {a.dtype}")
        out = cls(ai, ai, a.shape, a.dtype).collapsed()
        # a constant's collapsed axes really are value-uniform (we know
        # the exact values — lo == hi)
        out.vuni = frozenset(ax for ax in range(out.lo.ndim)
                             if out.lo.shape[ax] == 1)
        return out

    @classmethod
    def from_range(cls, aval, lo: int, hi: int) -> "AbsVal":
        shape = tuple(aval.shape)
        one = (1,) * len(shape)
        # only size-1 concrete extents are value-uniform: a traced
        # input varies freely within its (uniform) bounds
        return cls(np.full(one, lo, np.int64), np.full(one, hi, np.int64),
                   shape, aval.dtype,
                   vuni=frozenset(ax for ax, s in enumerate(shape)
                                  if s == 1))

    @classmethod
    def top(cls, aval) -> "AbsVal":
        lo, hi = interval_for_dtype(aval.dtype)
        return cls.from_range(aval, lo, hi)

    # ---------------- views ----------------

    def materialize(self, axes: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """lo/hi broadcast to concrete size along the given axes."""
        tgt = list(self.lo.shape)
        for ax in axes:
            tgt[ax] = self.shape[ax]
        return (np.broadcast_to(self.lo, tgt), np.broadcast_to(self.hi, tgt))

    def full(self) -> Tuple[np.ndarray, np.ndarray]:
        return (np.broadcast_to(self.lo, self.shape),
                np.broadcast_to(self.hi, self.shape))

    def collapsed(self) -> "AbsVal":
        """Shrink every axis along which both bounds are uniform to 1."""
        lo, hi = self.lo, self.hi
        for ax in range(lo.ndim):
            if lo.shape[ax] > 1:
                l0 = np.take(lo, [0], axis=ax)
                h0 = np.take(hi, [0], axis=ax)
                if (lo == l0).all() and (hi == h0).all():
                    lo, hi = l0, h0
        return AbsVal(lo, hi, self.shape, self.dtype, self.excl,
                      self.vuni)

    def max_abs(self) -> int:
        if self.lo.size == 0:
            return 0
        return int(max(abs(int(self.lo.min())), abs(int(self.hi.max()))))

    def join(self, other: "AbsVal") -> "AbsVal":
        lo = np.minimum(self.lo, other.lo)
        hi = np.maximum(self.hi, other.hi)
        # a property must hold on both sides to hold on the union
        return AbsVal(lo, hi, self.shape, self.dtype,
                      self.excl & other.excl, self.vuni & other.vuni)

    def contains(self, other: "AbsVal") -> bool:
        return bool((self.lo <= other.lo).all() and
                    (other.hi <= self.hi).all())

    def equals(self, other: "AbsVal") -> bool:
        return bool(np.array_equal(self.lo, other.lo) and
                    np.array_equal(self.hi, other.hi))

    def same(self, other: "AbsVal") -> bool:
        """Full abstract-state equality (bounds + refinements) — the
        reuse criterion for incremental evaluation."""
        return (self.equals(other) and self.excl == other.excl and
                self.vuni == other.vuni)

    def __repr__(self):
        return (f"AbsVal([{int(self.lo.min()) if self.lo.size else 0}, "
                f"{int(self.hi.max()) if self.hi.size else 0}] "
                f"{self.dtype}{self.shape})")


def _binop_arrays(a: AbsVal, b: AbsVal):
    """Broadcast-aligned stored arrays for an elementwise binary op."""
    nd = max(a.lo.ndim, b.lo.ndim)

    def lift(x):
        return x.reshape((1,) * (nd - x.ndim) + x.shape)
    return (lift(a.lo), lift(a.hi), lift(b.lo), lift(b.hi))


def _bitmask_bound(hi: np.ndarray) -> np.ndarray:
    """Smallest all-ones mask covering ``hi`` (elementwise, hi >= 0):
    7 -> 7, 8 -> 15, 2^32-1 -> 2^32-1. The sound upper bound for
    OR/XOR of non-negative values — neither can set a bit above the
    highest bit of either operand."""
    h = np.maximum(np.asarray(hi, dtype=np.int64), 0)
    out = np.zeros_like(h)
    nz = h > 0
    if nz.any():
        bits = np.ceil(np.log2(h[nz].astype(np.float64) + 1.0))
        cand = (np.int64(1) << bits.astype(np.int64)) - 1
        # float rounding safety: the mask must COVER hi
        short = cand < h[nz]
        cand = np.where(short, (cand << 1) | 1, cand)
        out[nz] = cand
    return _clamp(out)


def _corner_minmax(fn, alo, ahi, blo, bhi):
    c1, c2, c3, c4 = fn(alo, blo), fn(alo, bhi), fn(ahi, blo), fn(ahi, bhi)
    lo = np.minimum(np.minimum(c1, c2), np.minimum(c3, c4))
    hi = np.maximum(np.maximum(c1, c2), np.maximum(c3, c4))
    return lo, hi


def _source_of(eqn) -> str:
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return f"{frame.file_name}:{frame.start_line}"
        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return "<unknown>"


# Widening ladder: 0, +-powers of two, +-SAT. Domain-specific thresholds
# (MASK, LOOSE_MAX, fold bounds) are appended by the caller via `hints`.
_BASE_LADDER = [0] + [1 << k for k in range(0, 62)]


class IntervalInterpreter:
    """Abstract interpreter: run with :meth:`eval_closed`, inspect
    ``violations``/``max_abs`` afterwards.

    Args:
      ladder_hints: extra widening thresholds (e.g. the limb layout's
        MASK/LOOSE_MAX) for the long-scan fixed-point fallback, so
        widened invariants land on the bounds the design intends
        instead of the next power of two.
      max_unroll: trip-count ceiling for exact scan unrolling; longer
        scans use the widened fixed point.
    """

    def __init__(self, ladder_hints: Sequence[int] = (),
                 widen_after: int = 8, max_fp_iters: int = 400,
                 max_unroll: int = 256):
        pos = sorted(set(_BASE_LADDER) |
                     {abs(int(h)) for h in ladder_hints} | {int(SAT)})
        self._ladder = np.array(
            sorted({-v for v in pos} | set(pos)), dtype=np.int64)
        self._widen_after = widen_after
        self._max_fp_iters = max_fp_iters
        self._max_unroll = max_unroll
        self.violations: List[Violation] = []
        self.max_abs: int = 0
        self._recording = True
        self._seen_sites: set = set()
        self._handlers: Dict[str, Callable] = self._build_handlers()

    # ---------------- public API ----------------

    def eval_closed(self, closed_jaxpr, invals: Sequence[AbsVal],
                    path: str = "jaxpr") -> List[AbsVal]:
        import jax.core as core
        jaxpr = closed_jaxpr.jaxpr if isinstance(
            closed_jaxpr, core.ClosedJaxpr) else closed_jaxpr
        consts = closed_jaxpr.consts if isinstance(
            closed_jaxpr, core.ClosedJaxpr) else []
        return self._eval(jaxpr, consts, list(invals), path)

    # ---------------- core loop ----------------

    def _eval(self, jaxpr, consts, invals, path) -> List[AbsVal]:
        import jax.core as core
        env: Dict = {}
        for var, c in zip(jaxpr.constvars, consts):
            env[var] = AbsVal.from_concrete(np.asarray(c))
        if len(jaxpr.invars) != len(invals):
            raise Unsupported(
                f"{path}: arity mismatch {len(jaxpr.invars)} vs "
                f"{len(invals)}")
        for var, v in zip(jaxpr.invars, invals):
            env[var] = v

        def read(v):
            if isinstance(v, core.Literal):
                return AbsVal.from_concrete(np.asarray(v.val))
            return env[v]

        for idx, eqn in enumerate(jaxpr.eqns):
            ins = [read(v) for v in eqn.invars]
            outs = self.run_eqn(eqn, ins, path, idx)
            for var, out in zip(eqn.outvars, outs):
                if not isinstance(var, core.DropVar):
                    env[var] = out
        return [read(v) for v in jaxpr.outvars]

    def _check(self, eqn, out: AbsVal, aval, path, idx) -> AbsVal:
        dlo, dhi = interval_for_dtype(aval.dtype)
        vlo = int(out.lo.min()) if out.lo.size else 0
        vhi = int(out.hi.max()) if out.hi.size else 0
        if vlo < dlo or vhi > dhi:
            if self._recording:
                site = (path, idx)
                if site not in self._seen_sites:
                    self._seen_sites.add(site)
                    self.violations.append(Violation(
                        path=path, eqn_index=idx,
                        primitive=eqn.primitive.name,
                        dtype=str(np.dtype(aval.dtype)), lo=vlo, hi=vhi,
                        dtype_min=dlo, dtype_max=dhi,
                        where=_source_of(eqn)))
            # wrap semantics: a wrapped value can be anything in range
            # (zero wraps to zero and equal values wrap equally, so
            # both refinements survive the clamp)
            out = AbsVal(np.clip(out.lo, dlo, dhi),
                         np.clip(out.hi, dlo, dhi), out.shape, out.dtype,
                         out.excl, out.vuni)
        if self._recording:
            self.max_abs = max(self.max_abs, out.max_abs())
        return out

    # ---------------- handlers ----------------

    def _build_handlers(self) -> Dict[str, Callable]:
        h = {
            "add": self._h_add, "add_any": self._h_add,
            "sub": self._h_sub, "mul": self._h_mul,
            "neg": self._h_neg, "abs": self._h_abs,
            "sign": self._h_sign,
            "max": self._h_max, "min": self._h_min,
            "and": self._h_and, "or": self._h_or, "not": self._h_not,
            "xor": self._h_xor,
            "shift_left": self._h_shift_left,
            "shift_right_arithmetic": self._h_shift_right_arith,
            "shift_right_logical": self._h_shift_right_logical,
            "eq": self._h_cmp, "ne": self._h_cmp, "lt": self._h_cmp,
            "le": self._h_cmp, "gt": self._h_cmp, "ge": self._h_cmp,
            "select_n": self._h_select_n,
            "convert_element_type": self._h_convert,
            "broadcast_in_dim": self._h_broadcast_in_dim,
            "reshape": self._h_reshape, "squeeze": self._h_squeeze,
            "transpose": self._h_transpose, "rev": self._h_rev,
            "concatenate": self._h_concatenate, "pad": self._h_pad,
            "slice": self._h_slice, "dynamic_slice": self._h_dynamic_slice,
            "iota": self._h_iota,
            "reduce_sum": self._h_reduce_sum,
            "reduce_max": self._h_reduce_max,
            "reduce_min": self._h_reduce_min,
            "reduce_and": self._h_reduce_and,
            "reduce_or": self._h_reduce_or,
            "scatter-add": self._h_scatter_add,
            "dot_general": self._h_dot_general,
            "device_put": self._h_identity, "copy": self._h_identity,
            "stop_gradient": self._h_identity,
            "pjit": self._h_pjit, "closed_call": self._h_pjit,
            "scan": self._h_scan,
        }
        return h

    # --- elementwise arithmetic ---

    def _out(self, eqn, lo, hi) -> AbsVal:
        aval = eqn.outvars[0].aval
        return AbsVal(_clamp(lo), _clamp(hi), aval.shape, aval.dtype)

    def _h_add(self, eqn, ins, path, idx):
        a, b = ins
        alo, ahi, blo, bhi = _binop_arrays(a, b)
        return self._out(eqn, alo + blo, ahi + bhi)

    def _h_sub(self, eqn, ins, path, idx):
        a, b = ins
        alo, ahi, blo, bhi = _binop_arrays(a, b)
        return self._out(eqn, alo - bhi, ahi - blo)

    def _h_mul(self, eqn, ins, path, idx):
        a, b = ins
        alo, ahi, blo, bhi = _binop_arrays(a, b)
        lo, hi = _corner_minmax(_safe_mul, alo, ahi, blo, bhi)
        out = self._out(eqn, lo, hi)
        # a product is nonzero only where BOTH factors are: exclusivity
        # along an axis survives from either factor
        nd = out.lo.ndim
        out.excl = frozenset(
            {ax + (nd - a.lo.ndim) for ax in a.excl} |
            {ax + (nd - b.lo.ndim) for ax in b.excl})
        return out

    def _h_neg(self, eqn, ins, path, idx):
        a = ins[0]
        return self._out(eqn, -a.hi, -a.lo)

    def _h_abs(self, eqn, ins, path, idx):
        a = ins[0]
        lo = np.where((a.lo <= 0) & (a.hi >= 0), 0,
                      np.minimum(np.abs(a.lo), np.abs(a.hi)))
        hi = np.maximum(np.abs(a.lo), np.abs(a.hi))
        return self._out(eqn, lo, hi)

    def _h_sign(self, eqn, ins, path, idx):
        a = ins[0]  # sign is monotone: corner bounds are exact
        return self._out(eqn, np.sign(a.lo), np.sign(a.hi))

    def _h_max(self, eqn, ins, path, idx):
        a, b = ins
        alo, ahi, blo, bhi = _binop_arrays(a, b)
        return self._out(eqn, np.maximum(alo, blo), np.maximum(ahi, bhi))

    def _h_min(self, eqn, ins, path, idx):
        a, b = ins
        alo, ahi, blo, bhi = _binop_arrays(a, b)
        return self._out(eqn, np.minimum(alo, blo), np.minimum(ahi, bhi))

    # --- bitwise ---

    def _h_and(self, eqn, ins, path, idx):
        a, b = ins
        alo, ahi, blo, bhi = _binop_arrays(a, b)
        if np.dtype(eqn.outvars[0].aval.dtype) == np.bool_:
            return self._out(eqn, np.minimum(alo, blo),
                             np.minimum(ahi, bhi))
        a_nn, b_nn = alo >= 0, blo >= 0
        hi = np.where(a_nn & b_nn, np.minimum(ahi, bhi),
                      np.where(a_nn, ahi,
                               np.where(b_nn, bhi,
                                        np.maximum(ahi, bhi))))
        lo = np.where(a_nn | b_nn, np.zeros_like(alo),
                      np.full_like(alo, -SAT))
        # exact when one side is a known submask-preserving range
        return self._out(eqn, lo, hi)

    def _h_or(self, eqn, ins, path, idx):
        a, b = ins
        alo, ahi, blo, bhi = _binop_arrays(a, b)
        if np.dtype(eqn.outvars[0].aval.dtype) == np.bool_:
            return self._out(eqn, np.maximum(alo, blo),
                             np.maximum(ahi, bhi))
        both_nn = (alo >= 0) & (blo >= 0)
        # x|y >= min(x, y) in all sign cases (setting bits moves a
        # negative toward -1); >= max(x, y) when both non-negative.
        lo = np.where(both_nn, np.maximum(alo, blo),
                      np.minimum(alo, blo))
        # x|y <= x + y for non-negative x, y; a possibly-negative
        # operand contributes 0 to the upper bound (result <= other|0).
        # Refinement (SHA-256 kernel): OR cannot set a bit above the
        # highest bit of either operand, so for non-negative operands
        # min in the power-of-two ceiling of max(ahi, bhi) — without
        # it, uint32 full-range ORs would falsely escape uint32.
        hi = _clamp(np.maximum(ahi, 0) + np.maximum(bhi, 0))
        hi = np.where(both_nn,
                      np.minimum(hi, _bitmask_bound(
                          np.maximum(ahi, bhi))), hi)
        return self._out(eqn, lo, hi)

    def _h_xor(self, eqn, ins, path, idx):
        a, b = ins
        alo, ahi, blo, bhi = _binop_arrays(a, b)
        if np.dtype(eqn.outvars[0].aval.dtype) == np.bool_:
            lo = np.where((alo == ahi) & (blo == bhi),
                          np.abs(alo - blo), np.zeros_like(alo))
            hi = np.where((alo == ahi) & (blo == bhi),
                          np.abs(alo - blo), np.ones_like(ahi))
            return self._out(eqn, lo, hi)
        both_nn = (alo >= 0) & (blo >= 0)
        lo = np.where(both_nn, np.zeros_like(alo), np.full_like(alo, -SAT))
        # same bit-ceiling refinement as OR: XOR of non-negative
        # operands never sets a bit above either operand's highest —
        # the bound that keeps the SHA-256 schedule/round XORs inside
        # uint32 instead of the (sound but useless) ahi + bhi.
        hi = np.where(both_nn,
                      np.minimum(_clamp(ahi + bhi),
                                 _bitmask_bound(np.maximum(ahi, bhi))),
                      np.full_like(ahi, SAT))
        return self._out(eqn, lo, hi)

    def _h_not(self, eqn, ins, path, idx):
        a = ins[0]
        dtype = np.dtype(eqn.outvars[0].aval.dtype)
        if dtype == np.bool_:
            return self._out(eqn, 1 - a.hi, 1 - a.lo)
        if dtype.kind == "u":
            # unsigned bitwise-not is dtype_max - x, not -1 - x
            _dlo, dhi = interval_for_dtype(dtype)
            return self._out(eqn, dhi - a.hi, dhi - a.lo)
        return self._out(eqn, -1 - a.hi, -1 - a.lo)

    def _h_shift_left(self, eqn, ins, path, idx):
        a, s = ins
        alo, ahi, slo, shi = _binop_arrays(a, s)
        slo = np.clip(slo, 0, 62)
        shi = np.clip(shi, 0, 62)

        def shl(x, k):
            return _safe_mul(x, np.int64(1) << k)
        lo, hi = _corner_minmax(shl, alo, ahi, slo, shi)
        return self._out(eqn, lo, hi)

    def _h_shift_right_arith(self, eqn, ins, path, idx):
        a, s = ins
        alo, ahi, slo, shi = _binop_arrays(a, s)
        slo = np.clip(slo, 0, 63)
        shi = np.clip(shi, 0, 63)
        lo, hi = _corner_minmax(np.right_shift, alo, ahi, slo, shi)
        return self._out(eqn, lo, hi)

    def _h_shift_right_logical(self, eqn, ins, path, idx):
        a, s = ins
        if int(a.lo.min()) < 0:
            # logical shift reinterprets the sign bit: bound by dtype
            dlo, dhi = interval_for_dtype(eqn.outvars[0].aval.dtype)
            return AbsVal.from_range(eqn.outvars[0].aval, 0, dhi)
        return self._h_shift_right_arith(eqn, ins, path, idx)

    # --- comparisons ---

    def _h_cmp(self, eqn, ins, path, idx):
        a, b = ins
        alo, ahi, blo, bhi = _binop_arrays(a, b)
        name = eqn.primitive.name
        if name in ("lt", "ge"):
            surely = ahi < blo          # a < b always
            never = alo >= bhi          # a < b never
            if name == "ge":
                surely, never = never, surely
        elif name in ("le", "gt"):
            surely = ahi <= blo
            never = alo > bhi
            if name == "gt":
                surely, never = never, surely
        elif name == "eq":
            surely = (alo == ahi) & (blo == bhi) & (alo == blo)
            never = (ahi < blo) | (bhi < alo)
        else:  # ne
            never = (alo == ahi) & (blo == bhi) & (alo == blo)
            surely = (ahi < blo) | (bhi < alo)
        lo = np.where(surely, 1, 0)
        hi = np.where(never, 0, 1)
        out = self._out(eqn, lo, hi)
        if name == "eq":
            out.excl = self._onehot_axes(a, b, out)
        return out

    @staticmethod
    def _onehot_axes(a: AbsVal, b: AbsVal, out: AbsVal) -> frozenset:
        """Axes along which eq(a, b) is one-hot: one side is a constant
        with pairwise-distinct values varying ONLY along that axis, the
        other side broadcast along it — the `iota == digit[None]`
        window-select idiom. At most one position can compare equal.

        Soundness hinges on the *concrete* (aval) size of the other
        side being 1 along the axis: broadcasting then guarantees the
        SAME runtime value at every position, so distinct constants can
        match at most once. Stored-size-1 would NOT be enough — that
        only means uniform *bounds*, and a value-varying operand (e.g.
        a traced (8,) input) could match every position."""
        axes = set()
        nd = out.lo.ndim
        for x, y in ((a, b), (b, a)):
            if not np.array_equal(x.lo, x.hi):
                continue  # not a constant
            for ax in range(x.lo.ndim):
                oax = ax + (nd - x.lo.ndim)
                if x.lo.shape[ax] != x.shape[ax] or x.shape[ax] <= 1:
                    continue
                if any(x.lo.shape[d] != 1
                       for d in range(x.lo.ndim) if d != ax):
                    continue  # constant varies along more than one axis
                if np.unique(x.lo).size != x.lo.size:
                    continue  # values not pairwise distinct
                yax = oax - (nd - y.lo.ndim)
                if yax >= 0 and y.shape[yax] != 1 and \
                        yax not in y.vuni:
                    continue  # the other side must carry the SAME
                    # runtime value at every position along the axis:
                    # size-1 concrete extent or a tracked broadcast
                    # (vuni) — uniform bounds alone are not enough
                axes.add(oax)
        return frozenset(axes)

    def _h_select_n(self, eqn, ins, path, idx):
        pred, *cases = ins
        plo, phi = pred.lo, pred.hi
        nd = max([c.lo.ndim for c in cases] + [plo.ndim])

        def lift(x):
            return x.reshape((1,) * (nd - x.ndim) + x.shape)
        plo, phi = lift(plo), lift(phi)
        lo = hi = None
        for k, c in enumerate(cases):
            clo, chi = lift(c.lo), lift(c.hi)
            selectable = (plo <= k) & (k <= phi)
            k_lo = np.where(selectable, clo, SAT)
            k_hi = np.where(selectable, chi, -SAT)
            lo = k_lo if lo is None else np.minimum(lo, k_lo)
            hi = k_hi if hi is None else np.maximum(hi, k_hi)
        return self._out(eqn, lo, hi)

    def _h_convert(self, eqn, ins, path, idx):
        a = ins[0]
        new = np.dtype(eqn.params["new_dtype"])
        if new == np.bool_:
            nonzero_sure = (a.lo > 0) | (a.hi < 0)
            zero_sure = (a.lo == 0) & (a.hi == 0)
            lo = np.where(nonzero_sure, 1, 0)
            hi = np.where(zero_sure, 0, 1)
            return AbsVal(lo, hi, eqn.outvars[0].aval.shape, new,
                          a.excl, a.vuni)
        if new.kind not in "iu":
            raise Unsupported(
                f"{path}[{idx}]: convert to {new} at {_source_of(eqn)}")
        if a.dtype == np.bool_ or a.dtype.kind in "iu":
            # zero converts to zero and equal values convert equally:
            # both refinements survive
            return AbsVal(a.lo, a.hi, eqn.outvars[0].aval.shape, new,
                          a.excl, a.vuni)
        raise Unsupported(f"{path}[{idx}]: convert from {a.dtype}")

    # --- structural ---

    def _h_identity(self, eqn, ins, path, idx):
        a = ins[0]
        aval = eqn.outvars[0].aval
        return AbsVal(a.lo, a.hi, aval.shape, aval.dtype)

    def _h_broadcast_in_dim(self, eqn, ins, path, idx):
        a = ins[0]
        aval = eqn.outvars[0].aval
        bdims = tuple(eqn.params["broadcast_dimensions"])
        if bdims != tuple(sorted(bdims)):
            raise Unsupported(f"{path}[{idx}]: permuted broadcast_in_dim")
        # source dim i lands at output dim bdims[i]; new and broadcast
        # (1 -> N) dims stay stored-1 (uniform by construction)
        tgt = [1] * len(aval.shape)
        for i, d in enumerate(bdims):
            tgt[d] = a.lo.shape[i]
        excl = frozenset(bdims[ax] for ax in a.excl)
        # value-uniform: new axes and size-1 -> N expansions replicate
        # ONE value by construction; mapped axes keep their tag
        vuni = set(range(len(aval.shape))) - set(bdims)
        for i, d in enumerate(bdims):
            if i in a.vuni or a.shape[i] == 1:
                vuni.add(d)
        return AbsVal(a.lo.reshape(tgt), a.hi.reshape(tgt),
                      aval.shape, aval.dtype, excl, frozenset(vuni))

    def _h_squeeze(self, eqn, ins, path, idx):
        a = ins[0]
        dims = eqn.params["dimensions"]
        lo = np.squeeze(a.lo, axis=tuple(dims))
        hi = np.squeeze(a.hi, axis=tuple(dims))
        aval = eqn.outvars[0].aval
        def remap(axes):
            return frozenset(ax - sum(1 for d in dims if d < ax)
                             for ax in axes if ax not in dims)
        return AbsVal(lo, hi, aval.shape, aval.dtype, remap(a.excl),
                      remap(a.vuni))

    def _h_transpose(self, eqn, ins, path, idx):
        a = ins[0]
        perm = eqn.params["permutation"]
        aval = eqn.outvars[0].aval
        def remap(axes):
            return frozenset(perm.index(ax) for ax in axes)
        return AbsVal(np.transpose(a.lo, perm), np.transpose(a.hi, perm),
                      aval.shape, aval.dtype, remap(a.excl),
                      remap(a.vuni))

    def _h_rev(self, eqn, ins, path, idx):
        a = ins[0]
        dims = [d for d in eqn.params["dimensions"]
                if a.lo.shape[d] > 1]
        lo, hi = a.lo, a.hi
        if dims:
            lo = np.flip(lo, axis=tuple(dims))
            hi = np.flip(hi, axis=tuple(dims))
        aval = eqn.outvars[0].aval
        return AbsVal(lo, hi, aval.shape, aval.dtype)

    def _h_reshape(self, eqn, ins, path, idx):
        a = ins[0]
        aval = eqn.outvars[0].aval
        if eqn.params.get("dimensions") is not None:
            raise Unsupported(f"{path}[{idx}]: reshape with dimensions")
        new_shape = tuple(aval.shape)
        # greedy group factoring: match products of old dims to new dims
        groups = self._reshape_groups(a.shape, new_shape)
        out_stored: Optional[List[int]] = [] if groups is not None else None
        excl, vuni = set(), set()
        if groups is not None:
            for in_dims, out_dims in groups:
                stored = [a.lo.shape[d] for d in in_dims]
                concrete = [a.shape[d] for d in in_dims]
                if len(in_dims) == 1 and len(out_dims) == 1:
                    if in_dims[0] in a.excl:
                        excl.add(out_dims[0])
                    if in_dims[0] in a.vuni:
                        vuni.add(out_dims[0])
                elif not in_dims:
                    vuni.update(out_dims)  # inserted size-1 axes
                if stored == concrete:
                    # fully materialized group: reshape carries through
                    out_stored.extend(new_shape[d] for d in out_dims)
                elif all(s == 1 for s in stored):
                    # fully collapsed group stays collapsed
                    out_stored.extend(1 for _ in out_dims)
                else:
                    out_stored = None  # mixed group: fall back
                    break
        if out_stored is None:
            lo, hi = a.full()
            out = AbsVal(lo.reshape(new_shape), hi.reshape(new_shape),
                         new_shape, aval.dtype)
            return out.collapsed()
        return AbsVal(a.lo.reshape(out_stored), a.hi.reshape(out_stored),
                      new_shape, aval.dtype, frozenset(excl),
                      frozenset(vuni))

    @staticmethod
    def _reshape_groups(old: Tuple[int, ...], new: Tuple[int, ...]):
        """Factor a reshape into (old_dims, new_dims) groups with equal
        products, or None if the greedy factorization fails."""
        groups = []
        i = j = 0
        while i < len(old) or j < len(new):
            gi, gj = [i], [j]
            if i >= len(old) or j >= len(new):
                # trailing 1s
                while i < len(old):
                    if old[i] != 1:
                        return None
                    groups.append(([i], []))
                    i += 1
                while j < len(new):
                    if new[j] != 1:
                        return None
                    groups.append(([], [j]))
                    j += 1
                break
            pi, pj = old[i], new[j]
            i += 1
            j += 1
            while pi != pj:
                if pi < pj:
                    if i >= len(old):
                        return None
                    pi *= old[i]
                    gi.append(i)
                    i += 1
                else:
                    if j >= len(new):
                        return None
                    pj *= new[j]
                    gj.append(j)
                    j += 1
            groups.append((gi, gj))
        return groups

    def _h_concatenate(self, eqn, ins, path, idx):
        dim = eqn.params["dimension"]
        aval = eqn.outvars[0].aval
        nd = len(aval.shape)
        # materialize the concat axis; broadcast others to a common shape
        los, his = [], []
        common = [1] * nd
        for a in ins:
            for d in range(nd):
                if d != dim:
                    common[d] = max(common[d], a.lo.shape[d])
        for a in ins:
            lo, hi = a.materialize([dim])
            tgt = list(common)
            tgt[dim] = lo.shape[dim]
            los.append(np.broadcast_to(lo, tgt))
            his.append(np.broadcast_to(hi, tgt))
        lo = np.concatenate(los, axis=dim)
        hi = np.concatenate(his, axis=dim)
        return AbsVal(lo, hi, aval.shape, aval.dtype).collapsed()

    def _h_pad(self, eqn, ins, path, idx):
        a, padval = ins
        cfg = eqn.params["padding_config"]
        aval = eqn.outvars[0].aval
        if any(lo < 0 or hi < 0 for lo, hi, _ in cfg):
            raise Unsupported(f"{path}[{idx}]: negative padding")
        pad_axes = [d for d, (l, h, i) in enumerate(cfg)
                    if (l, h, i) != (0, 0, 0)]
        lo, hi = a.materialize(pad_axes)
        out_stored = []
        for d, (l, h, i) in enumerate(cfg):
            if d in pad_axes:
                out_stored.append(aval.shape[d])
            else:
                out_stored.append(lo.shape[d])
        plo = int(padval.lo.min())
        phi = int(padval.hi.max())
        out_lo = np.full(out_stored, plo, np.int64)
        out_hi = np.full(out_stored, phi, np.int64)
        sl = []
        for d, (l, h, i) in enumerate(cfg):
            if d in pad_axes:
                n = lo.shape[d]
                sl.append(slice(l, l + (n - 1) * (i + 1) + 1 if n else l,
                                i + 1))
            else:
                sl.append(slice(None))
        out_lo[tuple(sl)] = lo
        out_hi[tuple(sl)] = hi
        return AbsVal(out_lo, out_hi, aval.shape, aval.dtype)

    def _h_slice(self, eqn, ins, path, idx):
        a = ins[0]
        starts = eqn.params["start_indices"]
        limits = eqn.params["limit_indices"]
        strides = eqn.params["strides"] or (1,) * len(starts)
        aval = eqn.outvars[0].aval
        sl = []
        for d, (s, l, st) in enumerate(zip(starts, limits, strides)):
            if a.lo.shape[d] == 1:
                sl.append(slice(0, 1, 1))
            else:
                sl.append(slice(s, l, st))
        return AbsVal(a.lo[tuple(sl)], a.hi[tuple(sl)],
                      aval.shape, aval.dtype)

    def _h_dynamic_slice(self, eqn, ins, path, idx):
        a = ins[0]
        starts = ins[1:]
        sizes = eqn.params["slice_sizes"]
        aval = eqn.outvars[0].aval
        lo, hi = a.lo, a.hi
        for d, (st, size) in enumerate(zip(starts, sizes)):
            dimsz = a.shape[d]
            s_lo = max(0, min(int(st.lo.min()), dimsz - size))
            s_hi = max(0, min(int(st.hi.max()), dimsz - size))
            if lo.shape[d] == 1:
                continue  # uniform along this axis: any window is equal
            if s_lo == s_hi:
                sl = [slice(None)] * lo.ndim
                sl[d] = slice(s_lo, s_lo + size)
                lo, hi = lo[tuple(sl)], hi[tuple(sl)]
            else:
                # union over feasible windows (sliding min/max)
                parts_lo, parts_hi = [], []
                for k in range(s_lo, s_hi + 1):
                    sl = [slice(None)] * lo.ndim
                    sl[d] = slice(k, k + size)
                    parts_lo.append(lo[tuple(sl)])
                    parts_hi.append(hi[tuple(sl)])
                lo = np.minimum.reduce(parts_lo)
                hi = np.maximum.reduce(parts_hi)
        return AbsVal(lo, hi, aval.shape, aval.dtype)

    def _h_iota(self, eqn, ins, path, idx):
        aval = eqn.outvars[0].aval
        dim = eqn.params["dimension"]
        n = aval.shape[dim]
        shape = [1] * len(aval.shape)
        shape[dim] = n
        vals = np.arange(n, dtype=np.int64).reshape(shape)
        return AbsVal(vals, vals.copy(), aval.shape, aval.dtype)

    # --- reductions ---

    def _reduce(self, eqn, ins, fn):
        a = ins[0]
        axes = sorted(eqn.params["axes"], reverse=True)
        lo, hi = a.lo, a.hi
        for ax in axes:
            lo = fn(lo, axis=ax)
            hi = fn(hi, axis=ax)
        aval = eqn.outvars[0].aval
        return AbsVal(lo, hi, aval.shape, aval.dtype)

    def _h_reduce_sum(self, eqn, ins, path, idx):
        a = ins[0]
        axes = sorted(eqn.params["axes"], reverse=True)
        lo, hi = a.lo, a.hi
        excl = set(a.excl)
        for ax in axes:
            n = a.shape[ax]
            if ax in excl:
                # at most one nonzero along ax: the sum is that single
                # element or zero — union bound, not an n-fold sum
                lo = np.minimum(lo.min(axis=ax), 0)
                hi = np.maximum(hi.max(axis=ax), 0)
            elif lo.shape[ax] == 1:
                lo = _clamp(_safe_mul(np.squeeze(lo, ax), np.int64(n)))
                hi = _clamp(_safe_mul(np.squeeze(hi, ax), np.int64(n)))
            else:
                lo = _clamp(_safe_sum(lo, ax))
                hi = _clamp(_safe_sum(hi, ax))
            excl = {e - 1 if e > ax else e for e in excl if e != ax}
        aval = eqn.outvars[0].aval
        return AbsVal(lo, hi, aval.shape, aval.dtype, frozenset(excl))

    def _h_reduce_max(self, eqn, ins, path, idx):
        return self._reduce(eqn, ins, np.max)

    def _h_reduce_min(self, eqn, ins, path, idx):
        return self._reduce(eqn, ins, np.min)

    def _h_reduce_and(self, eqn, ins, path, idx):
        # AND over an axis: true iff all true — min of lows / min of highs
        return self._reduce(eqn, ins, np.min)

    def _h_reduce_or(self, eqn, ins, path, idx):
        return self._reduce(eqn, ins, np.max)

    # --- scatter-add (the `.at[i].add(v)` fixup in table_select) ---

    def _h_scatter_add(self, eqn, ins, path, idx):
        operand, indices, updates = ins
        dn = eqn.params["dimension_numbers"]
        aval = eqn.outvars[0].aval
        if not np.array_equal(indices.lo, indices.hi):
            raise Unsupported(
                f"{path}[{idx}]: scatter-add with non-constant indices")
        idx_vals = np.broadcast_to(indices.lo, indices.shape)
        sdims = tuple(dn.scatter_dims_to_operand_dims)
        if idx_vals.size != len(sdims):
            raise Unsupported(
                f"{path}[{idx}]: scatter-add with multiple scatter "
                "points")
        if tuple(dn.inserted_window_dims) != sdims:
            raise Unsupported(f"{path}[{idx}]: scatter-add window shape")
        coords = [int(v) for v in idx_vals.ravel()]
        # materialize operand along indexed dims (the update makes them
        # non-uniform); updates broadcast into the window slice
        lo, hi = operand.materialize(list(sdims))
        lo, hi = lo.copy(), hi.copy()
        sl = [slice(None)] * lo.ndim
        ok = True
        for d, c in zip(sdims, coords):
            if not (0 <= c < operand.shape[d]):
                ok = False  # FILL_OR_DROP: out-of-bounds update dropped
            sl[d] = slice(c, c + 1)
        if ok:
            win_dims = [d for d in range(lo.ndim) if d not in sdims]
            if len(dn.update_window_dims) != updates.lo.ndim:
                raise Unsupported(
                    f"{path}[{idx}]: scatter-add update rank "
                    f"{updates.lo.ndim} vs window dims "
                    f"{dn.update_window_dims}")
            ulo, uhi = updates.lo, updates.hi
            tgt = [1] * lo.ndim
            for ud, d in enumerate(win_dims):
                tgt[d] = ulo.shape[ud] if ud < ulo.ndim else 1
            lo[tuple(sl)] = _clamp(lo[tuple(sl)] + ulo.reshape(tgt))
            hi[tuple(sl)] = _clamp(hi[tuple(sl)] + uhi.reshape(tgt))
        return AbsVal(lo, hi, aval.shape, aval.dtype)

    # --- dot_general (defensive: none in the current kernel) ---

    def _h_dot_general(self, eqn, ins, path, idx):
        a, b = ins
        (lhs_c, rhs_c), (lhs_b, rhs_b) = eqn.params["dimension_numbers"]
        aval = eqn.outvars[0].aval
        if lhs_b or rhs_b:
            raise Unsupported(f"{path}[{idx}]: batched dot_general")
        alo, ahi = a.full()
        blo, bhi = b.full()
        lhs_free = [d for d in range(alo.ndim)
                    if d not in lhs_c]
        rhs_free = [d for d in range(blo.ndim)
                    if d not in rhs_c]
        # einsum over the four corner products
        import string
        letters = string.ascii_lowercase
        l_sub = [""] * alo.ndim
        r_sub = [""] * blo.ndim
        k = 0
        for lc, rc in zip(lhs_c, rhs_c):
            l_sub[lc] = r_sub[rc] = letters[k]
            k += 1
        out_sub = ""
        for d in lhs_free:
            l_sub[d] = letters[k]
            out_sub += letters[k]
            k += 1
        for d in rhs_free:
            r_sub[d] = letters[k]
            out_sub += letters[k]
            k += 1
        spec = f"{''.join(l_sub)},{''.join(r_sub)}->{out_sub}"

        def dot(x, y):
            return np.einsum(spec, x.astype(np.float64),
                             y.astype(np.float64))
        c = [dot(alo, blo), dot(alo, bhi), dot(ahi, blo), dot(ahi, bhi)]
        # elementwise product bounds would be tighter; corner bound is
        # sound because min/max of sums <= sums of min/max per corner
        lo_f = np.minimum.reduce(c)
        hi_f = np.maximum.reduce(c)
        lo = np.where(np.abs(lo_f) >= float(SAT),
                      np.where(lo_f > 0, SAT, -SAT),
                      lo_f.astype(np.int64))
        hi = np.where(np.abs(hi_f) >= float(SAT),
                      np.where(hi_f > 0, SAT, -SAT),
                      hi_f.astype(np.int64))
        return AbsVal(lo, hi, aval.shape, aval.dtype).collapsed()

    # --- nesting ---

    def _h_pjit(self, eqn, ins, path, idx):
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        name = eqn.params.get("name", "call")
        return self.eval_closed(inner, ins, f"{path}/{name}@{idx}")

    # --- scan ---
    #
    # Every loop in the verify kernel is a fori_loop with a static trip
    # count (pinned by tests/test_kernel_cost.py), so the primary
    # strategy is EXACT unrolling: per-iteration bounds, no widening, no
    # over-approximation. A join fixed point cannot close over the loop
    # counter anyway (f([0,n]) = [1,n+1] for an incrementing index — no
    # finite f-closed set exists), so bounded iteration is the only
    # sound route; the incremental evaluator below makes it cheap by
    # re-evaluating only the body equations whose inputs changed since
    # the previous iteration (after a few iterations the limb bounds
    # stabilize and only the index chain recomputes). Scans longer than
    # ``max_unroll`` fall back to a widened fixed point whose carry is
    # clamped through the dtype check — sound, possibly imprecise, and
    # loud about it if the imprecision reaches a violation.

    def _h_scan(self, eqn, ins, path, idx):
        p = eqn.params
        if p.get("reverse"):
            raise Unsupported(f"{path}[{idx}]: reverse scan")
        body = p["jaxpr"]
        length = int(p["length"])
        nc, ncar = int(p["num_consts"]), int(p["num_carry"])
        consts = ins[:nc]
        init = ins[nc:nc + ncar]
        xs = ins[nc + ncar:]
        spath = f"{path}/scan@{idx}"

        def xs_elem_at(t: int) -> List[AbsVal]:
            out = []
            for x in xs:
                if x.lo.shape[0] == 1:
                    lo, hi = x.lo[0:1], x.hi[0:1]
                else:
                    lo, hi = x.lo[t:t + 1], x.hi[t:t + 1]
                out.append(AbsVal(np.squeeze(lo, 0), np.squeeze(hi, 0),
                                  x.shape[1:], x.dtype))
            return out

        def finish(carry_out: List[AbsVal], ys: List[AbsVal]):
            outs = list(carry_out)
            for y, outvar in zip(ys, eqn.outvars[ncar:]):
                yl = y.lo[np.newaxis]
                yh = y.hi[np.newaxis]
                outs.append(AbsVal(yl, yh, outvar.aval.shape,
                                   outvar.aval.dtype))
            return outs

        if length <= self._max_unroll:
            evaluator = _IncrementalBody(self, body, spath)
            # intern per-iteration xs slices: reuse the previous slice
            # OBJECT when bounds are equal so the evaluator's
            # change-propagation can skip everything downstream of an
            # unchanged window (e.g. uniform digit rows)
            carry = list(init)
            prev_x: Optional[List[AbsVal]] = None
            ys_join: Optional[List[AbsVal]] = None
            for t in range(length):
                xe = xs_elem_at(t)
                if prev_x is not None:
                    xe = [px if px.same(x) else x
                          for px, x in zip(prev_x, xe)]
                prev_x = xe
                outs = evaluator.run(list(consts) + carry + xe)
                newc = outs[:ncar]
                carry = [pc if pc.same(n) else n
                         for pc, n in zip(carry, newc)]
                ys_t = outs[ncar:]
                if ys_join is None:
                    ys_join = list(ys_t)
                else:
                    ys_join = [a.join(b) for a, b in zip(ys_join, ys_t)]
            return finish(carry, ys_join or [])
        return self._scan_fixed_point(eqn, consts, init, xs, body,
                                      length, ncar, spath, finish)

    def _scan_fixed_point(self, eqn, consts, init, xs, body, length,
                          ncar, spath, finish):
        def xs_joined() -> List[AbsVal]:
            out = []
            for x in xs:
                out.append(AbsVal(x.lo.min(axis=0), x.hi.max(axis=0),
                                  x.shape[1:], x.dtype))
            return out

        def run_body(carry, xelems, recording: bool) -> List[AbsVal]:
            saved = self._recording
            self._recording = recording
            try:
                return self.eval_closed(
                    body, list(consts) + list(carry) + list(xelems),
                    spath)
            finally:
                self._recording = saved

        ladder = np.array(sorted(set(self._ladder.tolist()) |
                                 {length, length + 1, -length}),
                          dtype=np.int64)
        xj = xs_joined()
        carry = list(init)
        converged = False
        for it in range(self._max_fp_iters):
            outs = run_body(carry, xj, recording=False)
            newc = [c.join(n) for c, n in zip(carry, outs[:ncar])]
            if all(c.equals(n) for c, n in zip(carry, newc)):
                converged = True
                break
            if it >= self._widen_after:
                newc = [self._widen(c, n, ladder)
                        for c, n in zip(carry, newc)]
            carry = newc
        if not converged:
            raise Unsupported(
                f"{spath}: carry fixed point did not converge in "
                f"{self._max_fp_iters} iterations")
        # recorded pass under the (dtype-clamped) invariant: checks
        # every body equation for all iterations at once
        outs = run_body(carry, xj, recording=self._recording)
        return finish(outs[:ncar], outs[ncar:])

    def run_eqn(self, eqn, ins: List[AbsVal], path: str,
                idx: int) -> List[AbsVal]:
        """Evaluate one equation (handler + dtype check). Shared by the
        main loop and the incremental body evaluator."""
        handler = self._handlers.get(eqn.primitive.name)
        if handler is None:
            raise Unsupported(
                f"{path}[{idx}]: unhandled primitive "
                f"'{eqn.primitive.name}' at {_source_of(eqn)}")
        outs = handler(eqn, ins, path, idx)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        return [self._check(eqn, o, var.aval, path, idx)
                for o, var in zip(outs, eqn.outvars)]

    @staticmethod
    def _widen(old: AbsVal, new: AbsVal, ladder: np.ndarray) -> AbsVal:
        lo, hi = new.lo.copy(), new.hi.copy()
        grow_lo = new.lo < old.lo
        grow_hi = new.hi > old.hi
        if grow_hi.any():
            pos = np.searchsorted(ladder, hi, side="left")
            pos = np.clip(pos, 0, len(ladder) - 1)
            hi = np.where(grow_hi, ladder[pos], hi)
        if grow_lo.any():
            pos = np.searchsorted(ladder, lo, side="right") - 1
            pos = np.clip(pos, 0, len(ladder) - 1)
            lo = np.where(grow_lo, ladder[pos], lo)
        return AbsVal(lo, hi, new.shape, new.dtype)


class _IncrementalBody:
    """Change-propagating evaluator for an unrolled scan body.

    Keeps the previous iteration's per-equation inputs (by object
    identity) and outputs: an equation whose input objects are unchanged
    is skipped outright; a recomputed output that EQUALS its predecessor
    is replaced by the predecessor object so everything downstream skips
    too. Once the limb bounds stabilize (2-3 iterations in practice),
    each remaining iteration only re-evaluates the loop-index chain and
    the window slices — turning O(length x body) into O(length) after a
    constant number of full passes. Bounds are identical to naive
    unrolling by construction (skips happen only on equality)."""

    def __init__(self, interp: IntervalInterpreter, closed_jaxpr,
                 path: str):
        import jax.core as core
        self._core = core
        self._interp = interp
        self._jaxpr = closed_jaxpr.jaxpr
        self._path = path
        self._const_env = {
            var: AbsVal.from_concrete(np.asarray(c))
            for var, c in zip(self._jaxpr.constvars, closed_jaxpr.consts)}
        self._lit_cache: Dict[Tuple[int, int], AbsVal] = {}
        n = len(self._jaxpr.eqns)
        self._prev_in: List[Optional[Tuple[int, ...]]] = [None] * n
        self._prev_out: List[Optional[List[AbsVal]]] = [None] * n

    def run(self, invals: Sequence[AbsVal]) -> List[AbsVal]:
        core = self._core
        env: Dict = dict(self._const_env)
        for var, v in zip(self._jaxpr.invars, invals):
            env[var] = v
        for idx, eqn in enumerate(self._jaxpr.eqns):
            ins = []
            for pos, v in enumerate(eqn.invars):
                if isinstance(v, core.Literal):
                    lit = self._lit_cache.get((idx, pos))
                    if lit is None:
                        lit = AbsVal.from_concrete(np.asarray(v.val))
                        self._lit_cache[(idx, pos)] = lit
                    ins.append(lit)
                else:
                    ins.append(env[v])
            in_ids = tuple(id(x) for x in ins)
            if in_ids == self._prev_in[idx]:
                outs = self._prev_out[idx]
            else:
                outs = self._interp.run_eqn(eqn, ins, self._path, idx)
                prev = self._prev_out[idx]
                if prev is not None:
                    outs = [p if p.same(o) else o
                            for p, o in zip(prev, outs)]
                self._prev_in[idx] = in_ids
                self._prev_out[idx] = outs
            for var, out in zip(eqn.outvars, outs):
                if not isinstance(var, core.DropVar):
                    env[var] = out
        out = []
        for v in self._jaxpr.outvars:
            if isinstance(v, core.Literal):
                out.append(AbsVal.from_concrete(np.asarray(v.val)))
            else:
                out.append(env[v])
        return out
