"""Whole-program lock-order prover: cycle detection + hold-and-block.

The PR 3 lock lint proves *mutation-under-lock inside one module*;
nothing machine-checked lock ORDERING across the fleet →
verify_service → batch_engine → device_health/metrics/tracing call
chain, and nothing flagged blocking work done while a lock is held.
This pass closes both gaps over the full ``locks.SCOPE``:

1. **Lock recovery** — every module-level ``threading.Lock/RLock/
   Condition`` and every ``self.<attr>`` lock assigned in a class body
   becomes a graph node (instance locks unify per class: two
   VerifyService replicas share the node ``VerifyService._cv``).
2. **Call resolution** — calls made lexically inside a ``with <lock>``
   region resolve across module boundaries: ``self.method``,
   module-level functions, imported-module functions
   (``batch_verifier.note_trace_event``), module-level singletons
   (``registry.meter``, ``slo_monitor.note_completion``,
   ``tenant_mod.tenant_slo.note_latency``) and the known
   engine/service/fleet seams (``rep["service"].submit``,
   ``self._verifier.submit``, ``svc.drain_handoff``) via
   :data:`RECEIVER_HINTS`. Unresolvable calls are skipped — the
   documented soundness limit (``docs/static_analysis.md`` §5).
3. **Acquisition graph** — holding L and (directly, or transitively
   through resolved calls) acquiring M adds the edge ``L -> M`` with
   its full call path. Any cycle is a deadlock finding printing every
   edge's acquisition path.
4. **Hold-and-block** — known-blocking operations (``cv.wait()``
   without a timeout, ``Queue.get()``/``join()`` without a timeout,
   ``time.sleep``, subprocess calls, socket I/O, device fetches,
   ``Executor.shutdown(wait=True)``) reachable while ANY lock is held
   are findings; each needs a written safety argument in
   :data:`ALLOWLIST` or a fix.

Deliberate lexical conventions shared with ``analysis/locks.py``:
nested ``def``/``lambda`` bodies run later, possibly outside the lock,
so they are analyzed as separate functions with nothing held; ``*_locked``
helpers are entered with their lock already held by the caller, which
is exactly how the call-through analysis reaches them.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

from stellar_tpu.analysis.lint_base import (
    Allowlist, Finding, LintReport, finish_report, repo_root,
)
from stellar_tpu.analysis.locks import SCOPE, _LOCK_CTORS

__all__ = ["run", "run_sources", "build_graph", "SCOPE",
           "ALLOWLIST", "RECEIVER_HINTS", "BLOCKING_KINDS"]

# ---------------- known seams ----------------
# Receiver-name → (module rel path, class) typing for attribute calls
# the pure syntactic resolver cannot see through. These are the
# engine/service/fleet seams the threaded tier actually crosses; the
# table is part of the pass's documented contract (§5) — a new seam
# must be added here to be traversed.
RECEIVER_HINTS: Dict[str, Tuple[str, str]] = {
    # VerifyService / FleetRouter hold a verifier; in the fleet it is
    # the SharedVerifier adapter, whose own module edge covers the
    # engine side — the direct hint covers the single-service wiring.
    "_verifier": ("stellar_tpu/crypto/batch_verifier.py",
                  "BatchVerifier"),
    # fleet replica records and the service module's own helpers pass
    # services around as `svc` / rep["service"]
    "svc": ("stellar_tpu/crypto/verify_service.py", "VerifyService"),
    "service": ("stellar_tpu/crypto/verify_service.py",
                "VerifyService"),
}

BLOCKING_KINDS = ("wait-untimed", "join-untimed", "queue-get", "sleep",
                  "subprocess", "socket", "device-fetch",
                  "executor-shutdown")

_SOCKET_OPS = {"recv", "recvfrom", "accept", "sendall",
               "create_connection"}
_SUBPROCESS_OPS = {"run", "Popen", "call", "check_call",
                   "check_output", "communicate"}
_DEVICE_FETCH_OPS = {"block_until_ready", "device_get", "device_put"}

ALLOWLIST = Allowlist({
    "stellar_tpu/utils/resilience.py": {
        "hold-and-block:WatchdogPool._loop.wait-untimed":
            "an IDLE pool worker parking on its own condition until "
            "a job arrives: Condition.wait releases the cv while "
            "parked, the daemon worker holds no other lock, and "
            "submit() notifies under the same cv — an unbounded park "
            "here is the pool's steady state, not a hang.",
    },
    "stellar_tpu/utils/native.py": {
        "hold-and-block:_load.subprocess":
            "one-shot compile-and-dlopen serialization: the lock "
            "exists precisely so exactly one thread runs g++ while "
            "late arrivals wait for the cached library; the compile "
            "is bounded (subprocess timeout=120) and happens once "
            "per process, before the threaded dispatch tier exists.",
    },
    "stellar_tpu/crypto/native_prep.py": {
        "hold-and-block:_load.subprocess":
            "same one-shot compile serialization as utils/native.py: "
            "the module lock makes the g++ build (timeout-bounded) "
            "happen exactly once; every later call is a cached-lib "
            "return that never blocks.",
    },
    "stellar_tpu/crypto/native_verify.py": {
        "hold-and-block:_load._build_lib.subprocess":
            "same one-shot compile serialization as utils/native.py, "
            "through the shared _build_lib helper: the module lock "
            "makes the g++ build (timeout-bounded) happen exactly "
            "once; every later call is a cached-lib return that "
            "never blocks.",
    },
    "stellar_tpu/soroban/native_wasm.py": {
        "hold-and-block:_load._build_lib.subprocess":
            "one-shot compile serialization (atomic publish protects "
            "concurrent PROCESSES; the lock serializes threads): the "
            "timeout-bounded g++ build in _build_lib runs once per "
            "process.",
        "hold-and-block:_load_ext._build_lib.subprocess":
            "same one-shot compile serialization as _load, for the "
            "CPython extension variant: timeout-bounded, once per "
            "process, before any dispatch-tier thread can contend.",
    },
})


def _name_of(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    return _name_of(node.func) in _LOCK_CTORS


# ---------------- module model ----------------

class _Module:
    """Syntactic model of one scoped module: its locks, functions,
    classes, singletons, and import aliases."""

    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        self.tree = tree
        self.module_locks: Set[str] = set()
        self.funcs: Dict[str, ast.AST] = {}       # qual -> def node
        self.func_class: Dict[str, Optional[str]] = {}
        self.class_locks: Dict[str, Set[str]] = {}
        self.instances: Dict[str, str] = {}       # global -> class name
        self.mod_aliases: Dict[str, str] = {}     # alias -> module rel
        self.obj_aliases: Dict[str, Tuple[str, str]] = {}  # name ->
        #                                   (module rel, name there)
        self._collect()

    def _collect(self) -> None:
        classes = [n for n in self.tree.body
                   if isinstance(n, ast.ClassDef)]
        class_names = {c.name for c in classes}
        for node in self.tree.body:
            if isinstance(node, ast.Assign):
                if _is_lock_ctor(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.module_locks.add(t.id)
                elif isinstance(node.value, ast.Call):
                    ctor = _name_of(node.value.func)
                    if ctor in class_names:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self.instances[t.id] = ctor
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.funcs[node.name] = node
                self.func_class[node.name] = None
        for cnode in classes:
            locks: Set[str] = set()
            for node in ast.walk(cnode):
                if isinstance(node, ast.Assign) and \
                        _is_lock_ctor(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            locks.add(t.attr)
            self.class_locks[cnode.name] = locks
            for node in cnode.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    qual = f"{cnode.name}.{node.name}"
                    self.funcs[qual] = node
                    self.func_class[qual] = cnode.name

    def index_imports(self, world: Dict[str, "_Module"]) -> None:
        """Map import aliases to scoped modules / their objects. Only
        names that land on another module in the analyzed world
        resolve; everything else is out of scope by design."""
        by_tail: Dict[str, str] = {}
        for rel in world:
            by_tail[rel[:-3].replace("/", ".")] = rel
            by_tail.setdefault(
                pathlib.PurePosixPath(rel).stem, rel)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    rel = by_tail.get(a.name)
                    if rel:
                        self.mod_aliases[a.asname or
                                         a.name.split(".")[0]] = rel
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    full = f"{node.module}.{a.name}"
                    local = a.asname or a.name
                    rel = by_tail.get(full)
                    if rel:
                        self.mod_aliases[local] = rel
                        continue
                    src_rel = by_tail.get(node.module)
                    if src_rel:
                        self.obj_aliases[local] = (src_rel, a.name)


# ---------------- the interprocedural pass ----------------

class _World:
    """The analyzed program: every scoped module, the acquisition
    graph, and the per-function acquire/block summaries."""

    def __init__(self, sources: Dict[str, str]):
        self.modules: Dict[str, _Module] = {}
        self.parse_errors: List[str] = []
        for rel, src in sources.items():
            try:
                self.modules[rel] = _Module(rel, ast.parse(src))
            except SyntaxError as e:  # pragma: no cover - guard
                self.parse_errors.append(f"{rel}: {e}")
        for m in self.modules.values():
            m.index_imports(self.modules)
        # fkey = (rel, qual)
        self._acq: Dict[tuple, Dict[str, list]] = {}
        self._blk: Dict[tuple, Dict[str, tuple]] = {}
        # lock -> lock -> example path (list of strings)
        self.edges: Dict[str, Dict[str, list]] = {}
        self.findings: List[Finding] = []

    # ---------- naming ----------

    def lock_id(self, rel: str, owner: Optional[str],
                attr: str) -> str:
        short = rel.rsplit("/", 1)[-1][:-3]
        return f"{short}.{owner}.{attr}" if owner else f"{short}.{attr}"

    # ---------- resolution ----------

    def resolve_receiver(self, node: ast.AST, mod: _Module,
                         cls: Optional[str]
                         ) -> Optional[Tuple[str, str]]:
        """(module rel, class name) a receiver expression denotes."""
        if isinstance(node, ast.Name):
            if node.id == "self" and cls:
                return (mod.rel, cls)
            if node.id in mod.instances:
                return (mod.rel, mod.instances[node.id])
            if node.id in mod.obj_aliases:
                src_rel, name = mod.obj_aliases[node.id]
                src = self.modules.get(src_rel)
                if src and name in src.instances:
                    return (src_rel, src.instances[name])
            if node.id in RECEIVER_HINTS:
                return RECEIVER_HINTS[node.id]
            return None
        if isinstance(node, ast.Attribute):
            # alias.obj  (tenant_mod.tenant_slo)
            if isinstance(node.value, ast.Name) and \
                    node.value.id in mod.mod_aliases:
                src = self.modules.get(mod.mod_aliases[node.value.id])
                if src and node.attr in src.instances:
                    return (src.rel, src.instances[node.attr])
            if node.attr in RECEIVER_HINTS:
                return RECEIVER_HINTS[node.attr]
            return None
        if isinstance(node, ast.Subscript):
            # rep["service"]
            sl = node.slice
            if isinstance(sl, ast.Constant) and \
                    isinstance(sl.value, str) and \
                    sl.value in RECEIVER_HINTS:
                return RECEIVER_HINTS[sl.value]
        return None

    def resolve_call(self, call: ast.Call, mod: _Module,
                     cls: Optional[str]) -> Optional[tuple]:
        """(module rel, qualname) of a call target, or None."""
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in mod.funcs:
                return (mod.rel, fn.id)
            if fn.id in mod.obj_aliases:
                src_rel, name = mod.obj_aliases[fn.id]
                src = self.modules.get(src_rel)
                if src and name in src.funcs:
                    return (src_rel, name)
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        recv = fn.value
        # module-level function via module alias
        if isinstance(recv, ast.Name) and recv.id in mod.mod_aliases:
            src = self.modules.get(mod.mod_aliases[recv.id])
            if src and fn.attr in src.funcs and \
                    src.func_class.get(fn.attr) is None:
                return (src.rel, fn.attr)
        target = self.resolve_receiver(recv, mod, cls)
        if target is not None:
            t_rel, t_cls = target
            t_mod = self.modules.get(t_rel)
            if t_mod is not None:
                qual = f"{t_cls}.{fn.attr}"
                if qual in t_mod.funcs:
                    return (t_rel, qual)
        return None

    def lock_of_with_item(self, expr: ast.AST, mod: _Module,
                          cls: Optional[str]) -> Optional[str]:
        if isinstance(expr, ast.Name) and expr.id in mod.module_locks:
            return self.lock_id(mod.rel, None, expr.id)
        if isinstance(expr, ast.Attribute):
            recv = expr.value
            if isinstance(recv, ast.Name) and \
                    recv.id in mod.mod_aliases:
                src = self.modules.get(mod.mod_aliases[recv.id])
                if src and expr.attr in src.module_locks:
                    return self.lock_id(src.rel, None, expr.attr)
            target = self.resolve_receiver(recv, mod, cls)
            if target is not None:
                t_rel, t_cls = target
                t_mod = self.modules.get(t_rel)
                if t_mod and expr.attr in \
                        t_mod.class_locks.get(t_cls, set()):
                    return self.lock_id(t_rel, t_cls, expr.attr)
        return None

    # ---------- blocking-op classification ----------

    @staticmethod
    def blocking_kind(call: ast.Call) -> Optional[str]:
        fn = call.func
        name = _name_of(fn)
        has_args = bool(call.args) or bool(call.keywords)
        kw = {k.arg for k in call.keywords}
        if name == "wait" and isinstance(fn, ast.Attribute) and \
                not call.args and "timeout" not in kw:
            return "wait-untimed"
        if name == "join" and isinstance(fn, ast.Attribute) and \
                not has_args:
            return "join-untimed"
        if name == "get" and isinstance(fn, ast.Attribute) and \
                not call.args and not kw:
            return "queue-get"
        if name == "sleep" and isinstance(fn, ast.Attribute) and \
                _name_of(fn.value) in ("time", "_time"):
            return "sleep"
        if isinstance(fn, ast.Attribute) and (
                (_name_of(fn.value) == "subprocess"
                 and name in _SUBPROCESS_OPS)
                or name == "communicate"):
            return "subprocess"
        if name in _SOCKET_OPS:
            return "socket"
        if name in _DEVICE_FETCH_OPS or (
                isinstance(fn, ast.Attribute)
                and _name_of(fn.value) == "jax"
                and name in ("device_get", "device_put")):
            return "device-fetch"
        if name == "shutdown" and isinstance(fn, ast.Attribute):
            waits = True
            for k in call.keywords:
                if k.arg == "wait" and \
                        isinstance(k.value, ast.Constant):
                    waits = bool(k.value.value)
            if call.args and isinstance(call.args[0], ast.Constant):
                waits = bool(call.args[0].value)
            if waits:
                return "executor-shutdown"
        return None

    # ---------- per-function summaries ----------

    def _fnode(self, fkey: tuple):
        mod = self.modules.get(fkey[0])
        return mod, (mod.funcs.get(fkey[1]) if mod else None)

    def _stmt_calls(self, node: ast.AST):
        """Calls in this statement's expressions, skipping nested
        defs/lambdas (deferred execution — analyzed separately)."""
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.expr):
                yield from self._expr_calls(sub)

    def _expr_calls(self, node: ast.AST):
        if isinstance(node, (ast.Lambda,)):
            return
        if isinstance(node, ast.Call):
            yield node
        for sub in ast.iter_child_nodes(node):
            yield from self._expr_calls(sub)

    def summaries(self, fkey: tuple, stack: frozenset = frozenset()
                  ) -> Tuple[Dict[str, list], Dict[str, tuple]]:
        """(acquires, blocks) reachable from calling ``fkey``:
        acquires maps lock -> example path; blocks maps blocking kind
        -> (example path, line)."""
        if fkey in self._acq:
            return self._acq[fkey], self._blk[fkey]
        if fkey in stack:  # recursion
            return {}, {}
        mod, node = self._fnode(fkey)
        if node is None:
            return {}, {}
        stack = stack | {fkey}
        acq: Dict[str, list] = {}
        blk: Dict[str, tuple] = {}
        cls = mod.func_class.get(fkey[1])
        here = f"{mod.rel}:{fkey[1]}"

        def visit(n: ast.AST):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # deferred body: separate analysis
                if isinstance(child, ast.With):
                    for item in child.items:
                        lk = self.lock_of_with_item(
                            item.context_expr, mod, cls)
                        if lk is not None:
                            acq.setdefault(lk, [
                                f"{here}:{child.lineno} acquires "
                                f"{lk}"])
                if isinstance(child, ast.stmt):
                    for call in self._stmt_calls(child):
                        kind = self.blocking_kind(call)
                        if kind is not None:
                            blk.setdefault(kind, (
                                [f"{here}:{call.lineno} {kind}"],
                                call.lineno))
                        tgt = self.resolve_call(call, mod, cls)
                        if tgt is not None:
                            a2, b2 = self.summaries(tgt, stack)
                            step = (f"{here}:{call.lineno} calls "
                                    f"{tgt[1]}")
                            for lk, path in a2.items():
                                acq.setdefault(lk, [step] + path)
                            for kd, (path, ln) in b2.items():
                                blk.setdefault(kd,
                                               ([step] + path, ln))
                visit(child)

        visit(node)
        self._acq[fkey] = acq
        self._blk[fkey] = blk
        return acq, blk

    # ---------- the main walk ----------

    def analyze(self) -> None:
        for rel, mod in sorted(self.modules.items()):
            for qual, node in sorted(mod.funcs.items()):
                self._analyze_function(mod, qual, node)

    def _edge(self, src: str, dst: str, path: List[str]) -> None:
        self.edges.setdefault(src, {}).setdefault(dst, path)

    def _analyze_function(self, mod: _Module, qual: str,
                          fnode: ast.AST) -> None:
        cls = mod.func_class.get(qual)
        here = f"{mod.rel}:{qual}"

        def scan(node: ast.AST, held: List[tuple]):
            # held: [(lock id, with line)]
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # deferred body, runs with nothing held
                inner = held
                if isinstance(child, ast.With):
                    got = []
                    for item in child.items:
                        lk = self.lock_of_with_item(
                            item.context_expr, mod, cls)
                        if lk is not None:
                            got.append((lk, child.lineno))
                    for lk, ln in got:
                        for hl, hln in held:
                            if hl != lk:
                                self._edge(hl, lk, [
                                    f"{here}:{hln} holds {hl}",
                                    f"{here}:{ln} acquires {lk}"])
                    inner = held + got
                if isinstance(child, ast.stmt) and held:
                    self._check_stmt(child, mod, cls, qual, here,
                                     held)
                scan(child, inner)

        scan(fnode, [])

    def _check_stmt(self, stmt: ast.stmt, mod: _Module,
                    cls: Optional[str], qual: str, here: str,
                    held: List[tuple]) -> None:
        held_names = [h for h, _ in held]
        for call in self._stmt_calls(stmt):
            kind = self.blocking_kind(call)
            if kind is not None and not self._wait_on_own_cv_timed(
                    call):
                self.findings.append(Finding(
                    file=mod.rel, line=call.lineno,
                    rule="hold-and-block",
                    symbol=f"{qual}.{kind}",
                    message=f"{kind} while holding "
                            f"{held_names} — blocking work under a "
                            f"lock wedges every contender"))
            tgt = self.resolve_call(call, mod, cls)
            if tgt is None:
                continue
            acq, blk = self.summaries(tgt)
            step = f"{here}:{call.lineno} calls {tgt[1]}"
            for lk, path in acq.items():
                for hl, hln in held:
                    if hl != lk:
                        self._edge(hl, lk, [
                            f"{here}:{hln} holds {hl}", step] + path)
            for kd, (path, _ln) in blk.items():
                self.findings.append(Finding(
                    file=mod.rel, line=call.lineno,
                    rule="hold-and-block",
                    symbol=f"{qual}.{tgt[1]}.{kd}",
                    message=f"{kd} reachable while holding "
                            f"{held_names} via "
                            f"{' -> '.join([step] + path)}"))

    @staticmethod
    def _wait_on_own_cv_timed(call: ast.Call) -> bool:
        """cv.wait(timeout) is bounded AND releases its own cv — never
        a finding (the untimed spelling is classified upstream)."""
        return False

    # ---------- cycles ----------

    def cycle_findings(self) -> List[Finding]:
        out: List[Finding] = []
        seen: Set[tuple] = set()
        for start in sorted(self.edges):
            cyc = self._find_cycle(start)
            if not cyc:
                continue
            canon = tuple(sorted(cyc))
            if canon in seen:
                continue
            seen.add(canon)
            parts = []
            for i, src in enumerate(cyc):
                dst = cyc[(i + 1) % len(cyc)]
                path = self.edges[src][dst]
                parts.append(f"[{src} -> {dst}] " + " -> ".join(path))
            sym = "->".join(cyc + [cyc[0]])
            out.append(Finding(
                file=self._lock_file(cyc[0]), line=1,
                rule="lock-cycle", symbol=sym,
                message="lock-acquisition cycle (potential "
                        "deadlock): " + " ; ".join(parts)))
        return out

    def _lock_file(self, lock: str) -> str:
        short = lock.split(".", 1)[0]
        for rel in self.modules:
            if rel.rsplit("/", 1)[-1][:-3] == short:
                return rel
        return short

    def _find_cycle(self, start: str) -> Optional[List[str]]:
        stack: List[str] = []
        on_stack: Set[str] = set()
        visited: Set[str] = set()

        def dfs(n: str) -> Optional[List[str]]:
            stack.append(n)
            on_stack.add(n)
            for m in sorted(self.edges.get(n, {})):
                if m == n:
                    return [n]  # self-cycle (re-entrant acquire)
                if m in on_stack:
                    return stack[stack.index(m):]
                if m not in visited:
                    got = dfs(m)
                    if got:
                        return got
            stack.pop()
            on_stack.discard(n)
            visited.add(n)
            return None

        return dfs(start)

    def graph(self) -> dict:
        locks: Set[str] = set(self.edges)
        for dsts in self.edges.values():
            locks.update(dsts)
        for mod in self.modules.values():
            for name in mod.module_locks:
                locks.add(self.lock_id(mod.rel, None, name))
            for cname, lset in mod.class_locks.items():
                for name in lset:
                    locks.add(self.lock_id(mod.rel, cname, name))
        return {
            "modules": sorted(self.modules),
            "locks": sorted(locks),
            "edges": {src: sorted(dsts)
                      for src, dsts in sorted(self.edges.items())},
        }


# ---------------- entry points ----------------

def run_sources(sources: Dict[str, str]
                ) -> Tuple[List[Finding], dict]:
    """Analyze a source map (rel path -> text); unit-test hook.
    Returns (raw findings, acquisition graph)."""
    world = _World(sources)
    world.analyze()
    findings = world.findings + world.cycle_findings()
    return findings, world.graph()


def _scope_sources(scope: Sequence[str]) -> Dict[str, str]:
    root = repo_root()
    out: Dict[str, str] = {}
    for rel in scope:
        p = root / rel
        if p.exists():
            out[rel] = p.read_text()
    return out


def build_graph(scope: Optional[Sequence[str]] = None) -> dict:
    """The acquisition graph of the real tree (tests / --json)."""
    world = _World(_scope_sources(scope or SCOPE))
    world.analyze()
    return world.graph()


def run(allowlist: Optional[Allowlist] = None) -> LintReport:
    allowlist = allowlist or ALLOWLIST
    sources = _scope_sources(SCOPE)
    findings, _graph = run_sources(sources)
    return finish_report("lockorder", len(sources), findings,
                         allowlist)
