"""Archive-I/O work nodes (reference ``src/historywork/``:
``GetHistoryArchiveStateWork``, ``BatchDownloadWork``,
``DownloadBucketsWork``, ``VerifyBucketWork``,
``VerifyLedgerChainWork``) — each download is its own retrying work, so
a flaky archive transport (e.g. a get-command subprocess) retries at
the granularity of one file, not the whole catchup."""

from __future__ import annotations

from typing import Dict, List, Optional

from stellar_tpu.history.history_manager import HistoryManager
from stellar_tpu.work.work import (
    RETRY_A_FEW, BasicWork, BatchWork, FunctionWork, State,
)

__all__ = [
    "GetHistoryArchiveStateWork", "GetCheckpointWork",
    "BatchDownloadWork", "DownloadVerifyBucketWork",
    "DownloadBucketsWork", "VerifyLedgerChainWork",
]


class GetHistoryArchiveStateWork(BasicWork):
    """Fetch + parse a HAS manifest (root ``.well-known`` when
    ``checkpoint`` is None); result in ``.has``."""

    def __init__(self, archive, checkpoint: Optional[int] = None,
                 max_retries: int = RETRY_A_FEW):
        name = f"get-has-{checkpoint if checkpoint is not None else 'root'}"
        super().__init__(name, max_retries)
        self.archive = archive
        self.checkpoint = checkpoint
        self.has = None

    def on_run(self) -> str:
        if self.checkpoint is None:
            self.has = HistoryManager.get_root_has(self.archive)
        else:
            self.has = HistoryManager.get_has(self.archive,
                                              self.checkpoint)
        return State.SUCCESS if self.has is not None else State.FAILURE


class GetCheckpointWork(BasicWork):
    """Download + parse one checkpoint's ledger/transactions/results
    category files into ``sink[checkpoint]``."""

    def __init__(self, archive, checkpoint: int, sink: Dict[int, tuple],
                 max_retries: int = RETRY_A_FEW):
        super().__init__(f"get-checkpoint-{checkpoint:08x}", max_retries)
        self.archive = archive
        self.checkpoint = checkpoint
        self.sink = sink

    def on_run(self) -> str:
        data = HistoryManager.get_checkpoint(self.archive,
                                             self.checkpoint)
        if data is None:
            return State.FAILURE
        self.sink[self.checkpoint] = data
        return State.SUCCESS


class BatchDownloadWork(BatchWork):
    """Bounded-parallel checkpoint downloads (reference
    ``BatchDownloadWork``); results land in ``.downloaded``."""

    def __init__(self, archive, checkpoints: List[int],
                 max_parallel: int = 8):
        super().__init__(f"batch-download-{len(checkpoints)}",
                         max_parallel)
        self.archive = archive
        self._todo = list(checkpoints)
        self._idx = 0
        self.downloaded: Dict[int, tuple] = {}

    def has_next(self) -> bool:
        return self._idx < len(self._todo)

    def yield_more_work(self) -> BasicWork:
        cp = self._todo[self._idx]
        self._idx += 1
        return GetCheckpointWork(self.archive, cp, self.downloaded)

    def on_reset(self):
        self._idx = 0
        self.downloaded.clear()
        super().on_reset()


class DownloadVerifyBucketWork(BasicWork):
    """Fetch one bucket by hash; ``HistoryManager.get_bucket``
    re-hashes the content against its name (the reference splits this
    into download + ``VerifyBucketWork``; the verification contract is
    identical)."""

    def __init__(self, archive, hexhash: str, sink: Dict[str, object],
                 max_retries: int = RETRY_A_FEW):
        super().__init__(f"get-bucket-{hexhash[:16]}", max_retries)
        self.archive = archive
        self.hexhash = hexhash
        self.sink = sink

    def on_run(self) -> str:
        try:
            if self.hexhash.startswith("hot:"):
                bucket = HistoryManager.get_hot_bucket(
                    self.archive, self.hexhash[4:])
            else:
                bucket = HistoryManager.get_bucket(self.archive,
                                                   self.hexhash)
        except ValueError:
            return State.FAILURE  # hash mismatch: corrupt download
        if bucket is None:
            return State.FAILURE
        self.sink[self.hexhash] = bucket
        return State.SUCCESS


class DownloadBucketsWork(BatchWork):
    """Bounded-parallel verified bucket downloads (reference
    ``DownloadBucketsWork``); results land in ``.buckets``."""

    def __init__(self, archive, hexhashes: List[str],
                 max_parallel: int = 8):
        uniq = sorted({h for h in hexhashes
                       if set(h.split(":")[-1]) != {"0"}})
        super().__init__(f"download-buckets-{len(uniq)}", max_parallel)
        self.archive = archive
        self._todo = uniq
        self._idx = 0
        self.buckets: Dict[str, object] = {}

    def has_next(self) -> bool:
        return self._idx < len(self._todo)

    def yield_more_work(self) -> BasicWork:
        h = self._todo[self._idx]
        self._idx += 1
        return DownloadVerifyBucketWork(self.archive, h, self.buckets)

    def on_reset(self):
        self._idx = 0
        self.buckets.clear()
        super().on_reset()


class VerifyLedgerChainWork(FunctionWork):
    """Backwards hash-chain verification over downloaded headers
    (reference ``VerifyLedgerChainWork``)."""

    def __init__(self, headers_provider):
        super().__init__("verify-ledger-chain", self._run)
        self._provider = headers_provider
        self.headers = []

    def _run(self) -> str:
        from stellar_tpu.catchup.catchup import verify_ledger_chain
        headers = self._provider()
        # empty = nothing to verify (target at/below the LCL): a no-op
        # success, matching the old inline chain-verify behavior —
        # failed downloads already failed the sequence upstream
        if not verify_ledger_chain(headers):
            return State.FAILURE
        self.headers = headers
        return State.SUCCESS
